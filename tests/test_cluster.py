"""Cluster coordination plane (cluster/): the r17 layers.

Unit lanes: HMAC security (sign/verify, skew, tamper), epoch registry
(stamps, staleness, bumps), histogram quantile, hedge delay math,
ring preference lists (the successor property replication relies on),
replicator qualification + transfer framing, membership leases against
the RESP stub, fleet brains (pressure + breaker suspicion), breaker
suspect semantics, scheduler fleet-degrade, cluster config validation.

Chaos lanes (``-m resilience``): a THREE-replica loopback cluster —
lease expiry mid-traffic, join warm-up byte identity, an epoch-stamped
purge beating an in-flight L2 fill, hedged peer fetch under a wedged
owner, split-brain bounded disagreement, owner-kill failover on a
replicated hot set, and the 403 matrix for the authenticated peer
surface.

r18 fleet lifecycle lanes: drain coordinator state machine, repair
digest/diff/rotation, quality tracker + suspicion quorum math,
lifecycle config validation, and the chaos drives — a rolling restart
of all three replicas under live traffic (zero 5xx, >= 0.95 warm
hits with the L2 flushed after every kill, lease/ring reconvergence),
anti-entropy healing a deliberately-dropped replica push within one
rotation, verbatim-replayed and v1 signatures 403ing, and an error-
storm replica demoted off the ring then restored.
"""

import asyncio
import json
import socket
import time

import numpy as np
import pytest
from aiohttp import ClientSession, web

from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.cache.plane.l2 import (
    RedisL2Tier,
    decode_entry_epoch,
    encode_entry,
)
from omero_ms_pixel_buffer_tpu.cache.plane.resp_stub import (
    InMemoryRespServer,
)
from omero_ms_pixel_buffer_tpu.cache.plane.ring import HashRing
from omero_ms_pixel_buffer_tpu.cache.result_cache import CachedTile
from omero_ms_pixel_buffer_tpu.cluster import (
    AntiEntropyRepairer,
    DrainCoordinator,
    EpochRegistry,
    FleetBrains,
    HedgePolicy,
    HotSetReplicator,
    MembershipManager,
    QualityTracker,
    RedisLink,
    SuspicionPolicy,
    build_digest,
    decode_transfer,
    encode_transfer,
    image_id_of,
    parse_digest,
)
from omero_ms_pixel_buffer_tpu.cluster.security import (
    SIG_HEADER,
    NonceCache,
    sign,
    verify,
)
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.resilience.breaker import (
    BOARD,
    CircuitBreaker,
)
from omero_ms_pixel_buffer_tpu.resilience import faultinject
from omero_ms_pixel_buffer_tpu.resilience.faultinject import INJECTOR
from omero_ms_pixel_buffer_tpu.resilience.scheduler import (
    SloScheduler,
)
from omero_ms_pixel_buffer_tpu.resilience.timeouts import set_io_timeout
from omero_ms_pixel_buffer_tpu.resilience import AdmissionController
from omero_ms_pixel_buffer_tpu.tile_ctx import TileCtx
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError
from omero_ms_pixel_buffer_tpu.utils.metrics import Histogram

rng = np.random.default_rng(17)
IMG = rng.integers(0, 60000, (1, 1, 2, 256, 256), dtype=np.uint16)
AUTH = {"Cookie": "sessionid=ck"}


@pytest.fixture(autouse=True)
def _clean_chaos():
    INJECTOR.clear()
    yield
    INJECTOR.clear()
    BOARD.reset()
    set_io_timeout(5.0)


# ---------------------------------------------------------------------------
# security: the HMAC peer surface
# ---------------------------------------------------------------------------

class TestSecurity:
    def test_sign_verify_round_trip(self):
        header = sign("s3cret", "POST", "/internal/purge/7", b"body")
        assert verify(
            "s3cret", header, "POST", "/internal/purge/7", b"body"
        )

    def test_wrong_secret_rejected(self):
        header = sign("s3cret", "GET", "/internal/transfer")
        assert not verify("other", header, "GET", "/internal/transfer")

    def test_tampered_fields_rejected(self):
        header = sign("s", "POST", "/internal/replica", b"frame")
        assert not verify("s", header, "POST", "/internal/replica",
                          b"other-frame")
        assert not verify("s", header, "GET", "/internal/replica",
                          b"frame")
        assert not verify("s", header, "POST", "/internal/purge/1",
                          b"frame")

    def test_clock_skew_window(self):
        now = time.time()
        header = sign("s", "GET", "/x", now=now - 3600)
        assert not verify("s", header, "GET", "/x", now=now)
        header = sign("s", "GET", "/x", now=now - 10)
        assert verify("s", header, "GET", "/x", now=now)
        # future-dated outside the window fails too
        header = sign("s", "GET", "/x", now=now + 3600)
        assert not verify("s", header, "GET", "/x", now=now)

    def test_malformed_headers_never_raise(self):
        for bad in (None, "", "v1", "v1:abc", "v2:1:aa", "v1:x:y",
                    "v1:" + "9" * 400 + ":zz"):
            assert not verify("s", bad, "GET", "/x")

    def test_v1_scheme_rejected(self):
        """The r17 nonce-less scheme is refused outright — keeping it
        verifiable would keep the replay window open."""
        import hashlib
        import hmac as hmac_mod

        ts = str(int(time.time()))
        message = "\n".join(
            ("GET", "/x", ts, hashlib.sha256(b"").hexdigest())
        ).encode()
        mac = hmac_mod.new(b"s", message, hashlib.sha256).hexdigest()
        assert not verify("s", f"v1:{ts}:{mac}", "GET", "/x")

    def test_replay_rejected_with_nonce_cache(self):
        cache = NonceCache()
        header = sign("s", "POST", "/internal/purge/7", b"b",
                      peer="p1")
        assert verify("s", header, "POST", "/internal/purge/7", b"b",
                      nonce_cache=cache, peer="p1")
        # the verbatim header again, inside the skew window: replay
        assert not verify("s", header, "POST", "/internal/purge/7",
                          b"b", nonce_cache=cache, peer="p1")
        assert cache.replays_rejected == 1

    def test_rotated_peer_name_cannot_dodge_the_nonce_cache(self):
        """The claimed peer identity is INSIDE the MAC: a captured
        signature re-presented under a different X-OMPB-Peer value
        fails the MAC check, so the per-peer nonce keying cannot be
        dodged (and invented peer names cannot flood the per-peer
        bounds)."""
        cache = NonceCache()
        header = sign("s", "POST", "/internal/purge/7", b"b",
                      peer="replica-a")
        assert verify("s", header, "POST", "/internal/purge/7", b"b",
                      nonce_cache=cache, peer="replica-a")
        for rotated in ("attacker-x", "replica-b", "-", ""):
            assert not verify(
                "s", header, "POST", "/internal/purge/7", b"b",
                nonce_cache=cache, peer=rotated,
            )

    def test_fresh_signatures_never_collide(self):
        """Two signings of the same request mint distinct nonces — a
        legitimate re-send is not a replay."""
        cache = NonceCache()
        h1 = sign("s", "POST", "/internal/purge/7", peer="p")
        h2 = sign("s", "POST", "/internal/purge/7", peer="p")
        assert h1 != h2
        assert verify("s", h1, "POST", "/internal/purge/7",
                      nonce_cache=cache, peer="p")
        assert verify("s", h2, "POST", "/internal/purge/7",
                      nonce_cache=cache, peer="p")

    def test_invalid_mac_never_burns_a_nonce(self):
        """Garbage traffic must not churn the cache: the nonce is
        recorded only after the MAC checks out, so an attacker cannot
        pre-burn a nonce it sniffed before the real request lands."""
        cache = NonceCache()
        header = sign("s", "GET", "/x", peer="p")
        parts = header.split(":")
        forged = ":".join(parts[:3] + ["0" * 64])
        assert not verify("s", forged, "GET", "/x",
                          nonce_cache=cache, peer="p")
        assert verify("s", header, "GET", "/x",
                      nonce_cache=cache, peer="p")

    def test_nonce_cache_bounded_per_peer(self):
        cache = NonceCache(max_peers=2, max_per_peer=4)
        now = time.time()
        for i in range(10):
            assert not cache.seen_or_record("a", f"n{i}", now=now)
        snap = cache.snapshot()
        assert snap["nonces"] <= 4
        # one peer's flood never evicts another's replay protection
        assert not cache.seen_or_record("b", "nb", now=now)
        for i in range(10):
            cache.seen_or_record("a", f"m{i}", now=now)
        assert cache.seen_or_record("b", "nb", now=now)  # still replay

    def test_nonce_expiry_prunes(self):
        cache = NonceCache(skew_s=1.0)
        t0 = time.time()
        assert not cache.seen_or_record("p", "n1", now=t0)
        # inside the window: replay
        assert cache.seen_or_record("p", "n1", now=t0 + 0.5)
        # far past the window the entry is pruned (the timestamp
        # check would reject such a stale header anyway)
        assert not cache.seen_or_record("p", "n1", now=t0 + 10.0)


# ---------------------------------------------------------------------------
# epochs
# ---------------------------------------------------------------------------

class TestEpochs:
    def test_image_id_parsing(self):
        assert image_id_of("img=42|z=0|c=0|q=x") == 42
        assert image_id_of("weird-key") is None
        assert image_id_of("") is None

    def test_note_known_monotonic(self):
        reg = EpochRegistry()
        assert reg.known(5) == 0
        reg.note(5, 3)
        reg.note(5, 1)  # regressions ignored
        assert reg.known(5) == 3

    def test_staleness(self):
        reg = EpochRegistry()
        reg.note(7, 2)
        assert reg.is_stale("img=7|z=0", None)      # unstamped = 0
        assert reg.is_stale("img=7|z=0", 1)
        assert not reg.is_stale("img=7|z=0", 2)
        assert not reg.is_stale("img=8|z=0", None)  # unknown image
        assert reg.stale_reads == 2

    def test_entry_epoch_round_trip(self):
        entry = CachedTile(b"tile-bytes", filename="t.png")
        frame = encode_entry(entry, epoch=9)
        got, epoch = decode_entry_epoch(frame)
        assert got.body == b"tile-bytes"
        assert got.etag == entry.etag
        assert epoch == 9
        got, epoch = decode_entry_epoch(encode_entry(entry))
        assert got.body == b"tile-bytes"
        assert epoch is None  # unstamped writer

    async def test_bump_against_stub(self):
        server = InMemoryRespServer()
        await server.start()
        link = RedisLink(server.uri)
        reg = EpochRegistry(link)
        try:
            assert await reg.bump(3) == 1
            assert await reg.bump(3) == 2
            assert reg.known(3) == 2
        finally:
            await link.close()
            await server.close()

    @pytest.mark.resilience
    async def test_bump_degrades_without_redis(self):
        link = RedisLink("redis://127.0.0.1:1")  # nobody listening
        reg = EpochRegistry(link)
        assert await reg.bump(3) is None
        # the LOCAL high-water mark still advanced: this replica's own
        # pushes/reads observe the purge even with Redis down
        assert reg.known(3) == 1
        await link.close()


# ---------------------------------------------------------------------------
# histogram quantile + hedge policy
# ---------------------------------------------------------------------------

class TestHistogramQuantile:
    def test_empty_is_none(self):
        h = Histogram("q_test_1", "t")
        assert h.quantile(0.99, stage="peer") is None

    def test_upper_bound_estimate(self):
        h = Histogram("q_test_2", "t")
        for _ in range(99):
            h.observe(0.004, stage="peer")
        h.observe(2.0, stage="peer")
        assert h.quantile(0.5, stage="peer") == 0.005
        assert h.quantile(0.99, stage="peer") == 0.005
        assert h.quantile(0.999, stage="peer") == 2.5

    def test_inf_bucket_resolves_to_largest_edge(self):
        h = Histogram("q_test_3", "t")
        h.observe(99.0)  # beyond every finite bucket
        assert h.quantile(0.5) == 10.0


class TestHedgePolicy:
    def test_disabled_is_none(self):
        assert HedgePolicy(enabled=False).delay_s() is None

    def test_fallback_when_no_samples(self, monkeypatch):
        p = HedgePolicy(enabled=True, min_s=0.01, max_s=0.5,
                        fallback_s=0.2)
        monkeypatch.setattr(
            HedgePolicy, "_observed_quantile", lambda self: None
        )
        assert p.delay_s() == 0.2

    def test_clamping(self, monkeypatch):
        p = HedgePolicy(enabled=True, min_s=0.05, max_s=0.25)
        monkeypatch.setattr(
            HedgePolicy, "_observed_quantile", lambda self: 0.001
        )
        assert p.delay_s() == 0.05
        monkeypatch.setattr(
            HedgePolicy, "_observed_quantile", lambda self: 3.0
        )
        assert p.delay_s() == 0.25
        monkeypatch.setattr(
            HedgePolicy, "_observed_quantile", lambda self: 0.1
        )
        assert p.delay_s() == 0.1


# ---------------------------------------------------------------------------
# ring preference lists
# ---------------------------------------------------------------------------

class TestRingOwners:
    MEMBERS = [f"http://replica-{i}:80" for i in range(5)]

    def test_owners_distinct_and_lead_with_owner(self):
        ring = HashRing(self.MEMBERS)
        for i in range(50):
            owners = ring.owners(f"img=1|x={i}", 3)
            assert owners[0] == ring.owner(f"img=1|x={i}")
            assert len(owners) == 3
            assert len(set(owners)) == 3

    def test_owners_capped_by_ring_size(self):
        ring = HashRing(self.MEMBERS[:2])
        assert len(ring.owners("k", 5)) == 2

    def test_successor_becomes_owner_after_departure(self):
        """THE replication property: when the owner leaves, the
        rebuilt ring maps each of its keys to exactly the next member
        on the old preference list — so the replica pushed there
        before the crash is a hit after it."""
        ring = HashRing(self.MEMBERS)
        for i in range(100):
            key = f"img=1|z=0|x={i}"
            owner, successor = ring.owners(key, 2)
            survivors = [m for m in self.MEMBERS if m != owner]
            rebuilt = HashRing(survivors)
            assert rebuilt.owner(key) == successor


# ---------------------------------------------------------------------------
# replicator + transfer framing
# ---------------------------------------------------------------------------

class TestReplicator:
    def test_targets_exclude_self(self):
        ring = HashRing(TestRingOwners.MEMBERS)
        key = "img=1|z=0|x=1"
        owner = ring.owner(key)
        rep = HotSetReplicator(owner, replication_factor=3)
        targets = rep.targets(ring, key)
        assert owner not in targets
        assert len(targets) == 2
        assert targets == ring.owners(key, 3)[1:]

    def test_qualification_and_push_dedupe(self):
        rep = HotSetReplicator("self", replication_factor=2,
                               hot_threshold=3)
        assert not rep.qualifies("k", 2)   # below the bar
        assert rep.qualifies("k", 3)
        assert rep.qualifies("k", None)    # no sketch: all fills hot
        rep.mark_pushed("k")
        assert not rep.qualifies("k", 99)  # once per ring
        rep.ring_changed()
        assert rep.qualifies("k", 3)       # new successors: re-push

    def test_factor_one_never_qualifies(self):
        rep = HotSetReplicator("self", replication_factor=1)
        assert not rep.qualifies("k", 99)

    def test_transfer_round_trip(self):
        items = [
            (f"img={i}|z=0", f"frame-{i}".encode() * 10)
            for i in range(5)
        ]
        assert decode_transfer(encode_transfer(items)) == items

    def test_transfer_torn_tail_keeps_prefix(self):
        body = encode_transfer([("k1", b"f1"), ("k2", b"f2")])
        assert decode_transfer(body[:-3]) == [("k1", b"f1")]
        assert decode_transfer(b"") == []
        assert decode_transfer(b"\xff\xff\xff\xff") == []


# ---------------------------------------------------------------------------
# membership against the RESP stub
# ---------------------------------------------------------------------------

class TestMembership:
    async def _link(self, server):
        return RedisLink(server.uri)

    async def test_leases_discover_each_other(self):
        server = InMemoryRespServer()
        await server.start()
        links = [RedisLink(server.uri) for _ in range(3)]
        urls = [f"http://r{i}:80" for i in range(3)]
        managers = [
            MembershipManager(links[i], urls[i], [urls[i]], 5.0)
            for i in range(3)
        ]
        try:
            for m in managers:
                assert await m.refresh_once()
            # the second round sees everyone's lease
            for m in managers:
                await m.refresh_once()
                assert list(m.members) == sorted(urls)
                assert not m.seeded
        finally:
            for link in links:
                await link.close()
            await server.close()

    async def test_lease_expiry_removes_member(self):
        server = InMemoryRespServer()
        await server.start()
        link_a = RedisLink(server.uri)
        link_b = RedisLink(server.uri)
        changes = []
        a = MembershipManager(
            link_a, "http://a:80", ["http://a:80"], 0.2,
            on_change=lambda add, rm, mem: changes.append((add, rm)),
        )
        b = MembershipManager(link_b, "http://b:80", ["http://b:80"],
                              0.2)
        try:
            await b.refresh_once()
            await a.refresh_once()
            assert "http://b:80" in a.members
            # b stops heartbeating; its lease expires within one TTL
            await asyncio.sleep(0.25)
            await a.refresh_once()
            assert "http://b:80" not in a.members
            joins = [c for c in changes if "http://b:80" in c[0]]
            leaves = [c for c in changes if "http://b:80" in c[1]]
            assert joins and leaves
        finally:
            await link_a.close()
            await link_b.close()
            await server.close()

    @pytest.mark.resilience
    async def test_redis_down_keeps_last_known_view(self):
        server = InMemoryRespServer()
        await server.start()
        link = RedisLink(server.uri)
        m = MembershipManager(
            link, "http://a:80", ["http://a:80", "http://seed:80"],
            5.0,
        )
        try:
            assert await m.refresh_once()
            before = m.members
            await server.close()
            assert not await m.refresh_once()
            assert m.members == before  # frozen, not collapsed
            assert m.refresh_failures == 1
        finally:
            await link.close()


# ---------------------------------------------------------------------------
# brains: fleet pressure + dependency suspicion
# ---------------------------------------------------------------------------

class TestBrains:
    async def test_publish_collect_round(self):
        server = InMemoryRespServer()
        await server.start()
        links = [RedisLink(server.uri) for _ in range(2)]
        urls = ["http://a:80", "http://b:80"]
        sched = SloScheduler(AdmissionController(max_inflight=4),
                             queue_size=8)
        a = FleetBrains(links[0], urls[0], scheduler=sched)
        b = FleetBrains(links[1], urls[1])
        try:
            assert await a.publish_once(1.0)
            assert await b.publish_once(1.0)
            assert await a.collect_once(urls)
            assert urls[1] in a.fleet
            assert a.fleet[urls[1]]["pressure"] == 0.0
        finally:
            for link in links:
                await link.close()
            await server.close()

    async def test_fleet_pressure_reaches_scheduler(self):
        server = InMemoryRespServer()
        await server.start()
        links = [RedisLink(server.uri) for _ in range(2)]
        sched = SloScheduler(AdmissionController(max_inflight=4),
                             queue_size=8)
        a = FleetBrains(links[0], "http://a:80", scheduler=sched)
        b = FleetBrains(links[1], "http://b:80")
        try:
            # fake a saturated peer brain
            payload = b.local_payload()
            payload["pressure"] = 1.0
            await links[1].command(
                b"SET", b"ompb:cluster:brain:http://b:80",
                json.dumps(payload).encode(),
            )
            await a.collect_once(["http://a:80", "http://b:80"])
            assert sched.fleet_pressure == 1.0
            assert sched.fleet_engaged
            # calm peer: disengages
            payload["pressure"] = 0.0
            await links[1].command(
                b"SET", b"ompb:cluster:brain:http://b:80",
                json.dumps(payload).encode(),
            )
            await a.collect_once(["http://a:80", "http://b:80"])
            assert not sched.fleet_engaged
        finally:
            for link in links:
                await link.close()
            await server.close()

    async def test_majority_open_dep_suspects_local_breaker(self):
        server = InMemoryRespServer()
        await server.start()
        link = RedisLink(server.uri)
        a = FleetBrains(link, "http://a:80")
        try:
            for url in ("http://b:80", "http://c:80"):
                await link.command(
                    b"SET", b"ompb:cluster:brain:" + url.encode(),
                    json.dumps({
                        "pressure": 0.0, "open": ["postgres:main"],
                    }).encode(),
                )
            await a.collect_once(
                ["http://a:80", "http://b:80", "http://c:80"]
            )
            assert a.suspected == ["postgres:main"]
            breaker = BOARD.create("postgres:main")
            assert breaker.snapshot()["suspect"]
            # ONE local failure trips a suspected breaker
            breaker.record_failure()
            assert breaker.state == "open"
        finally:
            await link.close()
            await server.close()

    async def test_collect_failure_decays_fleet_state(self):
        """Redis dying mid-outage must NOT freeze a saturated fleet
        view: stale pressure degrading an idle replica for the whole
        outage would invert the degradation contract (per-process
        behavior is the fallback)."""
        server = InMemoryRespServer()
        await server.start()
        link = RedisLink(server.uri)
        sched = SloScheduler(AdmissionController(max_inflight=4),
                             queue_size=8)
        a = FleetBrains(link, "http://a:80", scheduler=sched)
        try:
            await link.command(
                b"SET", b"ompb:cluster:brain:http://b:80",
                json.dumps({"pressure": 1.0, "open": []}).encode(),
            )
            await a.collect_once(["http://a:80", "http://b:80"])
            assert sched.fleet_engaged
        finally:
            await link.close()
            await server.close()
        # the stub is gone: the failed round reads as a calm fleet
        assert not await a.collect_once(
            ["http://a:80", "http://b:80"]
        )
        assert not sched.fleet_engaged
        assert sched.fleet_pressure == 0.0

    async def test_two_replica_fleet_single_peer_is_quorum(self):
        """With exactly one reporting peer, that peer IS the fleet's
        voice (suspicion still needs a local failure to confirm); at
        three reporting peers the bar is a strict majority of 2."""
        server = InMemoryRespServer()
        await server.start()
        link = RedisLink(server.uri)
        a = FleetBrains(link, "http://a:80")
        try:
            await link.command(
                b"SET", b"ompb:cluster:brain:http://b:80",
                json.dumps({"open": ["redis:sess"]}).encode(),
            )
            await a.collect_once(["http://a:80", "http://b:80"])
            assert a.suspected == ["redis:sess"]
        finally:
            await link.close()
            await server.close()

    async def test_minority_report_does_not_suspect(self):
        server = InMemoryRespServer()
        await server.start()
        link = RedisLink(server.uri)
        a = FleetBrains(link, "http://a:80")
        try:
            await link.command(
                b"SET", b"ompb:cluster:brain:http://b:80",
                json.dumps({"open": ["redis:sess"]}).encode(),
            )
            await link.command(
                b"SET", b"ompb:cluster:brain:http://c:80",
                json.dumps({"open": []}).encode(),
            )
            await link.command(
                b"SET", b"ompb:cluster:brain:http://d:80",
                json.dumps({"open": []}).encode(),
            )
            await a.collect_once([
                "http://a:80", "http://b:80", "http://c:80",
                "http://d:80",
            ])
            assert a.suspected == []
        finally:
            await link.close()
            await server.close()


class TestBreakerSuspect:
    def test_suspect_trips_on_first_failure(self):
        b = CircuitBreaker("dep", failure_threshold=5)
        b.suspect()
        b.record_failure()
        assert b.state == "open"

    def test_success_clears_suspicion(self):
        b = CircuitBreaker("dep", failure_threshold=5)
        b.suspect()
        b.record_success()
        b.record_failure()
        assert b.state == "closed"  # back to the full budget

    def test_clear_suspect(self):
        b = CircuitBreaker("dep", failure_threshold=5)
        b.suspect()
        b.clear_suspect()
        b.record_failure()
        assert b.state == "closed"

    def test_gossip_alone_never_opens(self):
        b = CircuitBreaker("dep", failure_threshold=5)
        for _ in range(10):
            b.suspect()
        assert b.state == "closed"
        b.allow()  # still admits traffic


class TestSchedulerFleetDegrade:
    async def test_fleet_engaged_degrades_uncontended_grants(self):
        from omero_ms_pixel_buffer_tpu.resilience import Deadline

        sched = SloScheduler(AdmissionController(max_inflight=4),
                             queue_size=8, degrade_factor=1.5)
        sched._service_ewma = 0.5  # estimated service: 500 ms
        tight = Deadline.after(0.2)
        # uncontended + calm fleet: full resolution
        assert not sched._degrade_flag(tight, contended=False)
        sched.note_fleet_pressure(1.0, engaged=True)
        assert sched._degrade_flag(tight, contended=False)
        # roomy deadline stays full-res even engaged
        assert not sched._degrade_flag(
            Deadline.after(5.0), contended=False
        )
        sched.note_fleet_pressure(0.0, engaged=False)
        assert not sched._degrade_flag(tight, contended=False)


# ---------------------------------------------------------------------------
# cluster config validation
# ---------------------------------------------------------------------------

class TestClusterConfigExtensions:
    BASE = {
        "cluster": {
            "members": ["http://a:1", "http://b:2"],
            "self": "http://a:1",
            "l2": {"uri": "redis://localhost:6379"},
        },
    }

    def _cfg(self, **cluster_extra):
        raw = {
            "session-store": {"type": "memory"},
            "cluster": {**self.BASE["cluster"], **cluster_extra},
        }
        return Config.from_dict(raw)

    def test_valid_extensions(self):
        cfg = self._cfg(**{
            "lease-ttl-s": 5.0, "replication-factor": 2,
            "transfer-max-entries": 64, "secret": "s3cret",
            "hedge": {"enabled": True, "min-ms": 10, "max-ms": 100},
        })
        cl = cfg.cluster
        assert cl.lease_ttl_s == 5.0
        assert cl.replication_factor == 2
        assert cl.transfer_max_entries == 64
        assert cl.secret == "s3cret"
        assert cl.hedge.enabled and cl.hedge.min_ms == 10.0

    def test_defaults_off(self):
        cfg = Config.from_dict({"session-store": {"type": "memory"}})
        cl = cfg.cluster
        assert cl.lease_ttl_s == 0.0
        assert cl.replication_factor == 1
        assert cl.secret is None
        assert not cl.hedge.enabled

    def test_lease_requires_l2(self):
        with pytest.raises(ConfigError, match="lease-ttl-s"):
            Config.from_dict({
                "session-store": {"type": "memory"},
                "cluster": {
                    "members": ["http://a:1"], "self": "http://a:1",
                    "lease-ttl-s": 5.0,
                },
            })

    def test_replication_requires_members(self):
        with pytest.raises(ConfigError, match="replication-factor"):
            Config.from_dict({
                "session-store": {"type": "memory"},
                "cluster": {
                    "l2": {"uri": "redis://x"},
                    "replication-factor": 2,
                },
            })

    def test_unknown_hedge_key_fails(self):
        with pytest.raises(ConfigError, match="hedge"):
            self._cfg(hedge={"enabled": True, "typo-ms": 5})

    def test_bad_quantile_fails(self):
        with pytest.raises(ConfigError, match="quantile"):
            self._cfg(hedge={"enabled": True, "quantile": 1.5})

    def test_bad_secret_fails(self):
        with pytest.raises(ConfigError, match="secret"):
            self._cfg(secret="   ")


# ---------------------------------------------------------------------------
# the three-replica loopback cluster
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Replica:
    def __init__(self, app_obj, url, runner):
        self.app = app_obj
        self.url = url
        self.runner = runner
        self.renders = []
        self.dead = False

    def count_renders(self):
        inner_handle = self.app.pipeline.handle
        inner_batch = self.app.pipeline.handle_batch

        def handle(ctx):
            self.renders.append(1)
            return inner_handle(ctx)

        def handle_batch(ctxs, **kw):
            self.renders.extend([1] * len(ctxs))
            return inner_batch(ctxs, **kw)

        self.app.pipeline.handle = handle
        self.app.pipeline.handle_batch = handle_batch

    async def kill(self):
        if not self.dead:
            self.dead = True
            await self.runner.cleanup()


async def _boot_replica(
    img_path, members, self_url, port, resp_uri, cluster_extra=None,
    cache_overrides=None,
):
    registry = ImageRegistry()
    registry.add(1, img_path)
    cluster_block = {
        "members": members,
        "self": self_url,
        "peer-timeout-ms": 3000,
    }
    if resp_uri:
        cluster_block["l2"] = {"uri": resp_uri}
    if cluster_extra:
        cluster_block.update(cluster_extra)
    config = Config.from_dict({
        "session-store": {"type": "memory"},
        "backend": {"batching": {"coalesce-window-ms": 1.0}},
        "cache": {
            "prefetch": {"enabled": False},
            **(cache_overrides or {}),
        },
        "cluster": cluster_block,
    })
    app_obj = PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=MemorySessionStore({"ck": "omero-key-1"}),
    )
    runner = web.AppRunner(app_obj.make_app())
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", port)
    await site.start()
    replica = _Replica(app_obj, self_url, runner)
    replica.count_renders()
    return replica


async def _make_cluster(
    tmp_path, n=3, cluster_extra=None, cache_overrides=None, l2=True,
    member_views=None,
):
    """Boot ``n`` replicas (aiohttp TCPSite on loopback) sharing one
    image fixture and one RESP stub. ``member_views`` overrides each
    replica's seed list (the split-brain lever)."""
    img_path = str(tmp_path / "img.ome.tiff")
    write_ome_tiff(img_path, IMG, tile_size=(64, 64), pyramid_levels=2)
    resp = None
    if l2:
        resp = InMemoryRespServer()
        await resp.start()
    ports = [_free_port() for _ in range(n)]
    members = [f"http://127.0.0.1:{p}" for p in ports]
    replicas = []
    for i, port in enumerate(ports):
        view = (
            member_views[i] if member_views is not None else members
        )
        replicas.append(await _boot_replica(
            img_path, view, members[i], port,
            resp.uri if resp else None,
            cluster_extra=cluster_extra,
            cache_overrides=cache_overrides,
        ))

    async def cleanup():
        for r in replicas:
            await r.kill()
        if resp is not None:
            await resp.close()

    return replicas, resp, cleanup


def _tile_paths(n):
    return [
        f"/tile/1/0/0/0?x={64 * (i % 4)}&y={64 * (i // 4)}&w=64&h=64"
        f"&format=png"
        for i in range(n)
    ]


def _hold_pipeline(replica, seconds):
    """Delay every render on one replica (single-lane AND batch
    paths) — the wedged/held-owner lever."""
    pipeline = replica.app.pipeline
    inner_handle = pipeline.handle
    inner_batch = pipeline.handle_batch

    def held(ctx):
        time.sleep(seconds)
        return inner_handle(ctx)

    def held_batch(ctxs, **kw):
        time.sleep(seconds)
        return inner_batch(ctxs, **kw)

    pipeline.handle = held
    pipeline.handle_batch = held_batch


def _key_for(app_obj, path):
    """The cache key a tile path resolves to on ``app_obj``."""
    query = dict(
        kv.split("=") for kv in path.split("?", 1)[1].split("&")
    )
    _, _, image_id, z, c, t = path.split("?", 1)[0].split("/")
    params = {"imageId": image_id, "z": z, "c": c, "t": t, **query}
    ctx = TileCtx.from_params(params, None)
    return ctx.cache_key(app_obj.pipeline.encode_signature())


async def _get(http, url, headers=AUTH):
    async def _one():
        async with http.get(url, headers=headers) as r:
            # keep the CIMultiDict: header case is transport detail
            return r.status, await r.read(), r.headers.copy()

    # hard client-side bound: a wedged replica must fail the test
    # loudly, never hang the suite
    return await asyncio.wait_for(_one(), 30.0)


# -- membership churn -------------------------------------------------------

class TestMembershipChurn:
    @pytest.mark.resilience
    async def test_lease_expiry_mid_traffic(self, tmp_path):
        """A replica dying mid-traffic expires off the ring within one
        lease TTL; survivors keep serving throughout (an extra render
        per disagreed key is the whole cost)."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=3, cluster_extra={"lease-ttl-s": 0.6},
        )
        try:
            await asyncio.sleep(0.5)  # leases discovered
            plane = replicas[0].app.cache_plane
            assert len(plane.membership.members) == 3
            paths = _tile_paths(8)
            async with ClientSession() as http:
                for i, path in enumerate(paths):
                    status, _b, _h = await _get(
                        http, replicas[i % 3].url + path
                    )
                    assert status == 200
                await replicas[2].kill()
                deadline = time.monotonic() + 5.0
                # traffic continues while the lease expires
                while time.monotonic() < deadline:
                    for r in replicas[:2]:
                        status, _b, _h = await _get(
                            http, r.url + paths[0]
                        )
                        assert status == 200
                    if len(plane.membership.members) == 2:
                        break
                    await asyncio.sleep(0.2)
            assert len(plane.membership.members) == 2
            assert replicas[2].url not in plane.membership.members
            assert plane.ring_version >= 1
            events = [
                e["event"] for e in plane.membership.events
                if e["url"] == replicas[2].url
            ]
            assert "leave" in events
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_join_warm_up_byte_identity(self, tmp_path):
        """A replica joining an established cluster pulls the hot set
        within ONE transfer round and serves it byte-identically —
        ETags included — without rendering."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2,
            cluster_extra={
                "lease-ttl-s": 0.6, "replication-factor": 2,
            },
        )
        joiner = None
        try:
            await asyncio.sleep(0.5)
            paths = _tile_paths(6)
            expect = {}
            async with ClientSession() as http:
                for i, path in enumerate(paths):
                    status, body, headers = await _get(
                        http, replicas[i % 2].url + path
                    )
                    assert status == 200
                    expect[path] = (body, headers["ETag"])
            # a fresh replica joins the same lease space
            port = _free_port()
            joiner = await _boot_replica(
                str(tmp_path / "img.ome.tiff"),
                [f"http://127.0.0.1:{port}"],
                f"http://127.0.0.1:{port}", port, resp.uri,
                cluster_extra={
                    "lease-ttl-s": 0.6, "replication-factor": 2,
                },
            )
            joiner.count_renders()
            await asyncio.sleep(0.6)  # first refresh + warm-up round
            warm = len(joiner.app.result_cache.memory)
            assert warm >= len(paths), warm
            # flush the shared L2 so a hit can only come from the
            # transferred local copy
            for key in [
                k for k in resp.data if k.startswith(b"ompb:tile:")
            ]:
                del resp.data[key]
            async with ClientSession() as http:
                for path in paths:
                    status, body, headers = await _get(
                        http, joiner.url + path
                    )
                    assert status == 200
                    assert headers.get("X-Cache") == "hit"
                    assert (body, headers["ETag"]) == expect[path]
            assert len(joiner.renders) == 0
        finally:
            if joiner is not None:
                await joiner.kill()
            await cleanup()

    @pytest.mark.resilience
    async def test_owner_kill_replicated_hot_set_stays_warm(
        self, tmp_path
    ):
        """The acceptance pin: kill the owner of a replicated hot set
        (with the shared L2 cold, so only the pushed replicas can
        answer) — the ring rebuild maps each key to exactly the
        successor that holds its replica, and >= 80% of the re-
        requests are hits."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=3,
            cluster_extra={
                "lease-ttl-s": 0.6, "replication-factor": 2,
            },
        )
        try:
            await asyncio.sleep(0.5)
            paths = _tile_paths(12)
            async with ClientSession() as http:
                # touch every tile TWICE through its owner: the second
                # (hit) crosses the TinyLFU hot bar and pushes to the
                # ring successor
                for path in paths:
                    key = _key_for(replicas[0].app, path)
                    owner_url = replicas[0].app.cache_plane.ring.owner(
                        key
                    )
                    owner = next(
                        r for r in replicas if r.url == owner_url
                    )
                    for _ in range(2):
                        status, _b, _h = await _get(
                            http, owner.url + path
                        )
                        assert status == 200
                await asyncio.sleep(0.5)  # pushes drain
                received = sum(
                    r.app.cache_plane.replicator.received
                    for r in replicas
                )
                assert received > 0
                victim = replicas[0]
                victim_keys = [
                    p for p in paths
                    if replicas[1].app.cache_plane.ring.owner(
                        _key_for(replicas[1].app, p)
                    ) == victim.url
                ]
                assert victim_keys  # the workload touched its range
                await victim.kill()
                # L2 cold: replication is the only warm copy
                for key in [
                    k for k in resp.data
                    if k.startswith(b"ompb:tile:")
                ]:
                    del resp.data[key]
                # survivors observe the lease expire + rebuild
                deadline = time.monotonic() + 5.0
                survivors = replicas[1:]
                while time.monotonic() < deadline:
                    if all(
                        len(r.app.cache_plane.membership.members) == 2
                        for r in survivors
                    ):
                        break
                    await asyncio.sleep(0.1)
                hits = 0
                for path in victim_keys:
                    key = _key_for(survivors[0].app, path)
                    new_owner_url = (
                        survivors[0].app.cache_plane.ring.owner(key)
                    )
                    new_owner = next(
                        r for r in survivors
                        if r.url == new_owner_url
                    )
                    status, _b, headers = await _get(
                        http, new_owner.url + path
                    )
                    assert status == 200
                    if headers.get("X-Cache") == "hit":
                        hits += 1
                rate = hits / len(victim_keys)
                assert rate >= 0.8, (hits, len(victim_keys))
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_split_brain_bounded_disagreement(self, tmp_path):
        """Two replicas with DISAGREEING member views: every tile
        still serves 200 with identical bytes/ETags from both, no
        forwarding loop forms, and the whole cost is bounded at one
        extra render per key (total renders <= 2x unique tiles)."""
        img_path = str(tmp_path / "img.ome.tiff")
        write_ome_tiff(
            img_path, IMG, tile_size=(64, 64), pyramid_levels=2
        )
        # explicit disagreement: A sees only itself, B sees both
        ports = [_free_port(), _free_port()]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        a = await _boot_replica(
            img_path, [members[0]], members[0], ports[0], None,
        )
        b = await _boot_replica(
            img_path, members, members[1], ports[1], None,
        )
        try:
            paths = _tile_paths(8)
            bodies = {}
            async with ClientSession() as http:
                for path in paths:
                    sa, body_a, ha = await _get(http, a.url + path)
                    sb, body_b, hb = await _get(http, b.url + path)
                    assert (sa, sb) == (200, 200)
                    assert body_a == body_b
                    assert ha["ETag"] == hb["ETag"]
                    bodies[path] = body_a
            total = len(a.renders) + len(b.renders)
            assert len(paths) <= total <= 2 * len(paths), total
        finally:
            await a.kill()
            await b.kill()


# -- epochs over the wire ---------------------------------------------------

class TestEpochInvalidation:
    @pytest.mark.resilience
    async def test_epoch_purge_beats_in_flight_fill(self, tmp_path):
        """A purge landing while a fill is mid-render wins: the fill
        reaches L2 stamped with the PRE-purge epoch and every
        epoch-aware reader treats it as a miss — invalidation is no
        longer TTL-backstopped."""
        replicas, resp, cleanup = await _make_cluster(tmp_path, n=2)
        try:
            path = _tile_paths(1)[0]
            key = _key_for(replicas[0].app, path)
            owner_url = replicas[0].app.cache_plane.ring.owner(key)
            owner = next(r for r in replicas if r.url == owner_url)
            other = next(r for r in replicas if r.url != owner_url)
            _hold_pipeline(owner, 0.4)  # hold renders past the purge
            async with ClientSession() as http:
                task = asyncio.ensure_future(
                    _get(http, owner.url + path)
                )
                await asyncio.sleep(0.1)  # mid-render
                owner.app._invalidate_image(1)  # bump + fan-out
                status, body, _h = await task
                assert status == 200
                await asyncio.sleep(0.3)  # fill's L2 publish drains
                # the stale fill IS physically in Redis ...
                raw_tier = RedisL2Tier(resp.uri)
                raw = await raw_tier._guarded(
                    b"GET", raw_tier._key(key)
                )
                await raw_tier.close()
                assert raw is not None
                entry, stamp = decode_entry_epoch(raw)
                assert entry is not None
                assert (stamp or 0) == 0  # pre-purge snapshot
                # ... but every epoch-aware reader calls it a miss:
                # the OTHER replica re-renders instead of serving it
                before = len(other.renders) + len(owner.renders)
                status, _b, headers = await _get(
                    http, other.url + path
                )
                assert status == 200
                assert headers.get("X-Cache") != "l2-hit"
                after = len(other.renders) + len(owner.renders)
                assert after == before + 1
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_purge_fan_out_carries_epoch(self, tmp_path):
        """Peer purges advance the receiver's local epoch high-water
        mark, so an in-flight replica push against a just-purged image
        is rejected without a Redis round trip."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2,
            cluster_extra={"replication-factor": 2},
        )
        try:
            receiver = replicas[1]
            plane0 = replicas[0].app.cache_plane
            replicas[0].app._invalidate_image(1)
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline:
                if receiver.app.cache_plane.epochs.known(1) >= 1:
                    break
                await asyncio.sleep(0.05)
            assert receiver.app.cache_plane.epochs.known(1) >= 1
            # a push stamped with the pre-purge epoch is stale here
            assert receiver.app.cache_plane.replica_push_stale(
                "img=1|z=0", 0
            )
            assert plane0.epochs.known(1) >= 1
        finally:
            await cleanup()


# -- hedging ----------------------------------------------------------------

class TestHedging:
    @pytest.mark.resilience
    async def test_hedged_fetch_under_wedged_owner(self, tmp_path):
        """The owner wedges mid-render: the non-owner's peer fetch
        runs past the hedge delay, the local render starts, wins, and
        the request completes far inside the peer timeout."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2, l2=False,
            cluster_extra={"hedge": {
                "enabled": True, "min-ms": 30, "max-ms": 80,
                "fallback-ms": 60,
            }},
        )
        try:
            path = _tile_paths(1)[0]
            key = _key_for(replicas[0].app, path)
            owner_url = replicas[0].app.cache_plane.ring.owner(key)
            owner = next(r for r in replicas if r.url == owner_url)
            other = next(r for r in replicas if r.url != owner_url)
            _hold_pipeline(owner, 1.2)  # the owner wedges
            t0 = time.monotonic()
            async with ClientSession() as http:
                status, body, headers = await _get(
                    http, other.url + path
                )
            elapsed = time.monotonic() - t0
            assert status == 200
            assert elapsed < 1.0, elapsed  # far under wedge + timeout
            hedge = other.app.cache_plane.hedge
            assert hedge.outcomes["fired"] >= 1
            assert hedge.outcomes["local_win"] >= 1
            assert len(other.renders) >= 1  # the hedge rendered here
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_hedge_peer_win_serves_peer_bytes(self, tmp_path):
        """The mirror case: the owner answers AFTER the hedge fires
        but BEFORE the local render finishes — the peer's bytes serve
        and the local flight is abandoned mid-wait (never killed)."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2, l2=False,
            cluster_extra={"hedge": {
                "enabled": True, "min-ms": 10, "max-ms": 40,
                "fallback-ms": 20,
            }},
        )
        try:
            path = _tile_paths(1)[0]
            key = _key_for(replicas[0].app, path)
            owner_url = replicas[0].app.cache_plane.ring.owner(key)
            owner = next(r for r in replicas if r.url == owner_url)
            other = next(r for r in replicas if r.url != owner_url)
            async with ClientSession() as http:
                # warm the owner so its answer is a fast cache hit —
                # but make the NON-owner's local render glacial
                status, owner_body, owner_h = await _get(
                    http, owner.url + path
                )
                assert status == 200
                _hold_pipeline(other, 1.0)  # glacial local render
                # owner round trips take ~ms; delay the exchange past
                # the hedge window with injected latency
                INJECTOR.install(
                    "cache.peer", faultinject.latency(0.08)
                )
                t0 = time.monotonic()
                status, body, headers = await _get(
                    http, other.url + path
                )
                elapsed = time.monotonic() - t0
            assert status == 200
            assert body == owner_body
            assert headers["ETag"] == owner_h["ETag"]
            assert headers.get("X-Cache") == "peer-hit"
            assert elapsed < 0.9, elapsed
            hedge = other.app.cache_plane.hedge
            assert hedge.outcomes["fired"] >= 1
            assert hedge.outcomes["peer_win"] >= 1
        finally:
            INJECTOR.clear()
            await cleanup()


class TestRingAppearsLater:
    async def test_dynamic_only_config_builds_peer_client(self):
        """A replica configured with ONLY itself + leases (the
        autoscaling shape: no static peer list) must still be able to
        peer-fetch once the first scan discovers a peer — the client
        exists from construction; only the ring is membership-fed."""
        from omero_ms_pixel_buffer_tpu.cache.plane import CachePlane

        server = InMemoryRespServer()
        await server.start()
        plane = CachePlane(
            members=("http://a:80",),
            self_url="http://a:80",
            l2_uri=server.uri,
            lease_ttl_s=5.0,
        )
        try:
            assert plane.peers is not None
            assert plane.membership is not None
            # a peer's lease appears: the rebuild must leave every
            # peer path (fetch/purge/push) with a live client
            plane._on_membership_change(
                ["http://b:80"], [],
                ("http://a:80", "http://b:80"),
            )
            assert plane.ring is not None
            assert len(plane.ring.members) == 2
            assert plane.ring_version == 1
        finally:
            await plane.close()
            await server.close()


# -- the authenticated peer surface -----------------------------------------

class TestClusterAuth:
    @pytest.mark.resilience
    async def test_unauthenticated_internal_surface_403s(
        self, tmp_path
    ):
        """With a secret configured, every /internal/* spelling —
        purge, replica push, transfer — answers 403 without a valid
        signature, peer marker or not; and a forged peer marker on a
        serving path 403s too."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2,
            cluster_extra={"secret": "fleet-secret"},
        )
        try:
            url = replicas[0].url
            async with ClientSession() as http:
                # no signature at all
                for method, path, body in (
                    ("POST", "/internal/purge/1", b""),
                    ("POST", "/internal/replica", b"frame"),
                    ("GET", "/internal/transfer", b""),
                ):
                    async with http.request(
                        method, url + path, data=body,
                        headers={"X-OMPB-Peer": "forged"},
                    ) as r:
                        assert r.status == 403, path
                # garbage signature
                async with http.post(
                    url + "/internal/purge/1",
                    headers={
                        "X-OMPB-Peer": "forged",
                        SIG_HEADER: "v1:123:deadbeef",
                    },
                ) as r:
                    assert r.status == 403
                # stale timestamp (outside the skew window)
                stale = sign(
                    "fleet-secret", "POST", "/internal/purge/1",
                    b"", now=time.time() - 3600,
                )
                async with http.post(
                    url + "/internal/purge/1",
                    headers={
                        "X-OMPB-Peer": "x", SIG_HEADER: stale,
                    },
                ) as r:
                    assert r.status == 403
                # a forged peer marker on a SERVING path — 403, and
                # the forged trace id is NEVER adopted into the
                # flight recorder (the obs middleware runs OUTSIDE
                # the guard so the 403 still records, but adoption
                # is gated on the same signature check)
                forged_tid = "f" * 32
                async with http.get(
                    url + _tile_paths(1)[0],
                    headers={
                        **AUTH,
                        "X-OMPB-Peer": "forged",
                        "X-OMPB-Trace-Id": forged_tid,
                        "X-OMPB-Trace-Span": "a" * 16,
                    },
                ) as r:
                    assert r.status == 403
                recorder = replicas[0].app.recorder
                assert all(
                    e["trace_id"] != forged_tid
                    for e in recorder.events()
                )
                # correctly signed (peer identity inside the MAC):
                # accepted
                good = sign(
                    "fleet-secret", "POST", "/internal/purge/1", b"",
                    peer="x",
                )
                async with http.post(
                    url + "/internal/purge/1",
                    headers={"X-OMPB-Peer": "x", SIG_HEADER: good},
                ) as r:
                    assert r.status == 200
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_signed_cluster_still_serves_and_purges(
        self, tmp_path
    ):
        """The whole plane keeps working WITH authentication on: peer
        fetches carry valid signatures, purge fan-out lands, and a
        browser request (no cluster identity) never pays the check."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2,
            cluster_extra={"secret": "fleet-secret"},
        )
        try:
            paths = _tile_paths(4)
            async with ClientSession() as http:
                for i, path in enumerate(paths):
                    s1, b1, h1 = await _get(
                        http, replicas[i % 2].url + path
                    )
                    s2, b2, h2 = await _get(
                        http, replicas[(i + 1) % 2].url + path
                    )
                    assert (s1, s2) == (200, 200)
                    assert b1 == b2 and h1["ETag"] == h2["ETag"]
                # purge fan-out (signed) reaches the peer
                replicas[0].app._invalidate_image(1)
                await asyncio.sleep(0.3)
                assert len(replicas[1].app.result_cache.memory) == 0
        finally:
            await cleanup()

    async def test_no_secret_keeps_peer_marker_posture(
        self, tmp_path
    ):
        """Without a secret the previous posture holds: /internal/*
        requires the peer marker (403 without), network policy is the
        boundary."""
        replicas, resp, cleanup = await _make_cluster(tmp_path, n=2)
        try:
            async with ClientSession() as http:
                async with http.post(
                    replicas[0].url + "/internal/purge/1"
                ) as r:
                    assert r.status == 403
                async with http.get(
                    replicas[0].url + "/internal/transfer"
                ) as r:
                    assert r.status == 403
                async with http.post(
                    replicas[0].url + "/internal/purge/1",
                    headers={"X-OMPB-Peer": "peer"},
                ) as r:
                    assert r.status == 200
        finally:
            await cleanup()


# -- replica push over the wire ---------------------------------------------

class TestReplicaPush:
    @pytest.mark.resilience
    async def test_stale_push_rejected(self, tmp_path):
        """An inbound replica push whose epoch stamp predates a purge
        this replica has seen is dropped — replication can never
        resurrect invalidated bytes."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2,
            cluster_extra={"replication-factor": 2},
        )
        try:
            receiver = replicas[1]
            receiver.app.cache_plane.epochs.note(1, 5)
            frame = encode_entry(
                CachedTile(b"stale-bytes", filename="t.png"), epoch=4
            )
            async with ClientSession() as http:
                async with http.post(
                    receiver.url + "/internal/replica",
                    data=frame,
                    headers={
                        "X-OMPB-Peer": "peer",
                        "X-OMPB-Key": "img=1|z=0|stale",
                    },
                ) as r:
                    assert r.status == 200
                    payload = await r.json()
            assert payload == {"stored": False, "stale": True}
            assert receiver.app.result_cache.contains(
                "img=1|z=0|stale"
            ) is False
            # a fresh-epoch push stores
            frame = encode_entry(
                CachedTile(b"fresh-bytes", filename="t.png"), epoch=5
            )
            async with ClientSession() as http:
                async with http.post(
                    receiver.url + "/internal/replica",
                    data=frame,
                    headers={
                        "X-OMPB-Peer": "peer",
                        "X-OMPB-Key": "img=1|z=0|fresh",
                    },
                ) as r:
                    assert (await r.json()) == {"stored": True}
            assert receiver.app.result_cache.contains(
                "img=1|z=0|fresh"
            )
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_healthz_reports_cluster(self, tmp_path):
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2,
            cluster_extra={
                "lease-ttl-s": 0.6, "replication-factor": 2,
                "secret": "s",
                "hedge": {"enabled": True},
            },
        )
        try:
            await asyncio.sleep(0.4)
            async with ClientSession() as http:
                async with http.get(
                    replicas[0].url + "/healthz"
                ) as r:
                    health = await r.json()
            cluster = health["cluster"]
            assert cluster["enabled"]
            assert cluster["authenticated"]
            assert cluster["membership"]["lease_ttl_s"] == 0.6
            assert len(cluster["membership"]["members"]) == 2
            assert cluster["replication"]["factor"] == 2
            assert cluster["hedge"]["enabled"]
            assert "brains" in cluster
            assert "epochs" in cluster
            assert "coord_link" in cluster
        finally:
            await cleanup()


# ---------------------------------------------------------------------------
# r18 fleet lifecycle: drain coordinator (unit)
# ---------------------------------------------------------------------------

class _FakePlane:
    """Duck-typed CachePlane for the drain state machine."""

    def __init__(self):
        self.calls = []

    def drain_propagation_s(self):
        return 0.0

    async def begin_drain(self):
        self.calls.append("begin")
        return True

    async def handoff_hot_set(self, deadline, clock=None):
        self.calls.append("handoff")
        return {"entries": 3, "targets": 1, "pushed": 3, "errors": 0}

    async def release_lease(self):
        self.calls.append("release")
        return True


class TestDrainCoordinator:
    async def test_protocol_order_and_idempotence(self):
        plane = _FakePlane()
        adm = AdmissionController(max_inflight=4)
        dc = DrainCoordinator(plane, deadline_s=2.0, admission=adm)
        r1, r2 = await asyncio.gather(dc.drain(), dc.drain())
        # concurrent triggers share one protocol run and one answer
        assert r1 == r2
        assert plane.calls == ["begin", "handoff", "release"]
        assert dc.state == "drained"
        assert r1["quiesced"] is True
        assert r1["handoff"]["pushed"] == 3

    async def test_quiescence_waits_for_inflight(self):
        plane = _FakePlane()
        adm = AdmissionController(max_inflight=4)
        assert adm.try_slot()
        dc = DrainCoordinator(plane, deadline_s=5.0, admission=adm)

        async def finish_later():
            await asyncio.sleep(0.25)
            adm.release()

        task = asyncio.ensure_future(finish_later())
        stats = await dc.drain()
        await task
        assert stats["quiesced"] is True
        assert stats["took_s"] >= 0.2

    async def test_deadline_bounds_stuck_inflight(self):
        plane = _FakePlane()
        adm = AdmissionController(max_inflight=4)
        assert adm.try_slot()  # never released: a wedged render
        dc = DrainCoordinator(plane, deadline_s=0.4, admission=adm)
        stats = await dc.drain()
        # the drain completes ANYWAY — bounded beats complete; the
        # straggler rides the crash path the fleet already survives
        assert stats["quiesced"] is False
        assert dc.state == "drained"
        assert plane.calls == ["begin", "handoff", "release"]
        adm.release()

    async def test_scheduler_stops_degrading_while_draining(self):
        from omero_ms_pixel_buffer_tpu.resilience import Deadline

        adm = AdmissionController(max_inflight=4)
        sched = SloScheduler(adm, queue_size=8)
        sched._service_ewma = 1.0
        tight = Deadline.after(0.01)
        assert sched._degrade_flag(tight, contended=True)
        sched.note_draining(True)
        assert not sched._degrade_flag(tight, contended=True)
        assert sched.snapshot()["draining"] is True
        sched.note_draining(False)
        assert sched._degrade_flag(tight, contended=True)


# ---------------------------------------------------------------------------
# r18 fleet lifecycle: anti-entropy repair (unit)
# ---------------------------------------------------------------------------

class TestRepairDigest:
    def test_digest_round_trip(self):
        items = [("img=1|a", 3), ("img=2|b", None), ("img=1|c", 0)]
        parsed = parse_digest(build_digest(items))
        assert parsed is not None
        assert [e["k"] for e in parsed["entries"]] == [
            "img=1|a", "img=2|b", "img=1|c"
        ]
        assert [e["ep"] for e in parsed["entries"]] == [3, None, 0]
        # the checksum is stable and content-sensitive
        assert parsed["sum"] == parse_digest(build_digest(items))["sum"]
        assert parsed["sum"] != parse_digest(
            build_digest(items[:2])
        )["sum"]

    def test_corrupt_digests_are_none(self):
        for bad in (b"", b"{", b"[]", b'{"entries": 3}',
                    b'{"entries": [{"ep": 1}]}'):
            out = parse_digest(bad)
            assert out is None or out["entries"] == []

    def test_select_missing_honors_the_replication_contract(self):
        ring = HashRing(("http://a", "http://b", "http://c"), 64)
        rep = AntiEntropyRepairer("http://b", max_keys=64)
        keys = [f"img=1|k{i}" for i in range(200)]
        # entries where a owns and b is the configured successor
        expected = [
            k for k in keys
            if ring.owners(k, 2)[0] == "http://a"
            and "http://b" in ring.owners(k, 2)[1:]
        ]
        digest = [{"k": k, "ep": None} for k in keys]
        wanted = rep.select_missing(
            "http://a", digest, ring, 2,
            has_local=lambda k: False,
            is_stale=lambda k, e: False,
        )
        assert wanted == expected[: len(wanted)]
        assert set(wanted) <= set(expected)
        # locally-present and epoch-stale entries never pull
        assert rep.select_missing(
            "http://a", digest, ring, 2,
            has_local=lambda k: True,
            is_stale=lambda k, e: False,
        ) == []
        assert rep.select_missing(
            "http://a", digest, ring, 2,
            has_local=lambda k: False,
            is_stale=lambda k, e: True,
        ) == []
        # factor 1: no replication contract, nothing to repair
        assert rep.select_missing(
            "http://a", digest, ring, 1,
            has_local=lambda k: False,
            is_stale=lambda k, e: False,
        ) == []

    def test_select_missing_bounded(self):
        ring = HashRing(("http://a", "http://b"), 64)
        rep = AntiEntropyRepairer("http://b", max_keys=5)
        digest = [
            {"k": key, "ep": None}
            for key in (f"img=1|k{i}" for i in range(500))
            if ring.owners(key, 2)[0] == "http://a"
        ]
        wanted = rep.select_missing(
            "http://a", digest, ring, 2,
            has_local=lambda k: False,
            is_stale=lambda k, e: False,
        )
        assert len(wanted) <= 5

    def test_unchanged_only_after_successful_sync(self):
        rep = AntiEntropyRepairer("http://b")
        assert not rep.unchanged("http://a", 42)
        # NOT recorded yet: a failed pull must not make the next
        # round skip the holes it failed to fill
        assert not rep.unchanged("http://a", 42)
        rep.note_synced("http://a", 42)
        assert rep.unchanged("http://a", 42)
        rep.ring_changed()
        assert not rep.unchanged("http://a", 42)

    def test_unchanged_skip_is_bounded(self):
        """The peer's checksum says nothing about LOCAL evictions —
        after MAX_SKIPS consecutive skips the round re-diffs, so a
        copy this replica dropped still heals in bounded rounds."""
        rep = AntiEntropyRepairer("http://b")
        rep.note_synced("http://a", 42)
        skipped = 0
        for _ in range(rep.MAX_SKIPS + 1):
            if rep.unchanged("http://a", 42):
                skipped += 1
        assert skipped == rep.MAX_SKIPS
        # the forced re-diff round resets the streak
        rep.note_synced("http://a", 42)
        assert rep.unchanged("http://a", 42)

    def test_next_peer_rotates(self):
        rep = AntiEntropyRepairer("http://b")
        peers = ["http://a", "http://c"]
        seen = [rep.next_peer(peers) for _ in range(4)]
        assert seen == ["http://a", "http://c"] * 2
        assert rep.next_peer([]) is None
        assert rep.next_peer(["http://b"]) is None  # only self


# ---------------------------------------------------------------------------
# r18 fleet lifecycle: quality suspicion (unit)
# ---------------------------------------------------------------------------

class TestQualityTracker:
    def test_window_counters_reset_on_take(self):
        q = QualityTracker()
        for _ in range(6):
            q.note(200, 0.01)
        q.note(500, 0.5)
        q.note(503, 0.2)
        w = q.take_window()
        assert w["n"] == 8 and w["err"] == 2
        assert q.take_window()["n"] == 0

    def test_p99_rolls_across_windows(self):
        q = QualityTracker()
        for _ in range(99):
            q.note(200, 0.010)
        q.note(200, 1.0)
        assert q.take_window()["p99_ms"] >= 900.0
        # the latency sample is rolling — the next window still has a
        # p99 even before new traffic
        assert q.take_window().get("p99_ms") is not None

    def test_4xx_is_not_an_error(self):
        q = QualityTracker()
        q.note(403, 0.01)
        q.note(404, 0.01)
        assert q.take_window()["err"] == 0


class TestSuspicionPolicy:
    def _brain(self, n=20, err=0, p99=10.0, bad=()):
        return {
            "q": {"n": n, "err": err, "p99_ms": p99},
            "bad": list(bad),
        }

    def test_error_rate_verdict(self):
        pol = SuspicionPolicy(enabled=True, error_rate=0.5)
        fleet = {
            "http://a": self._brain(),
            "http://b": self._brain(err=15),
        }
        assert pol.verdicts(fleet, {}) == ["http://b"]

    def test_p99_vs_fleet_median_verdict(self):
        pol = SuspicionPolicy(enabled=True, p99_factor=3.0)
        fleet = {
            "http://a": self._brain(p99=10.0),
            "http://b": self._brain(p99=12.0),
            "http://c": self._brain(p99=200.0),
        }
        assert pol.verdicts(fleet, {}) == ["http://c"]

    def test_min_requests_floor(self):
        """Too-thin self-reports are never judged — a replica that
        served 2 requests and failed one is noise, not a verdict."""
        pol = SuspicionPolicy(enabled=True, min_requests=8)
        fleet = {"http://b": self._brain(n=2, err=2)}
        assert pol.verdicts(fleet, {}) == []

    def test_peer_failure_verdict_catches_silent_sickness(self):
        """The replica too sick to even self-report rides the peer-
        observed clause."""
        pol = SuspicionPolicy(enabled=True, peer_failures=3)
        fleet = {"http://b": {"q": None}}
        assert pol.verdicts(fleet, {"http://b": 3}) == ["http://b"]
        assert pol.verdicts(fleet, {"http://b": 2}) == []

    def test_peer_with_no_brain_at_all_is_still_judged(self):
        """A replica whose brain key is ABSENT (expired, publish
        failing, wedged before first publish) must still earn a
        verdict from this collector's own observed failures — the
        silent ones are exactly who the clause exists for."""
        pol = SuspicionPolicy(enabled=True, peer_failures=3)
        assert pol.verdicts({}, {"http://c": 3}) == ["http://c"]
        fleet = {"http://a": self._brain()}
        assert pol.verdicts(fleet, {"http://c": 5}) == ["http://c"]

    def test_demotion_needs_strict_majority(self):
        pol = SuspicionPolicy(enabled=True)
        members = ("http://a", "http://b", "http://c")
        # 3 reporters (2 peer brains + self): need 2 votes
        fleet = {
            "http://a": self._brain(bad=["http://c"]),
            "http://b": self._brain(),
        }
        assert pol.demoted(fleet, [], members) == []  # 1 vote
        assert pol.demoted(
            fleet, ["http://c"], members
        ) == ["http://c"]  # 2 votes
        # disabled: never demotes
        off = SuspicionPolicy(enabled=False)
        assert off.demoted(fleet, ["http://c"], members) == []

    def test_demotion_never_empties_the_ring(self):
        pol = SuspicionPolicy(enabled=True)
        members = ("http://a", "http://b")
        fleet = {
            "http://a": self._brain(bad=["http://a", "http://b"]),
            "http://b": self._brain(bad=["http://a", "http://b"]),
        }
        out = pol.demoted(
            fleet, ["http://a", "http://b"], members
        )
        assert len(out) <= len(members) - 1


# ---------------------------------------------------------------------------
# r18 config validation: drain / repair / suspect blocks
# ---------------------------------------------------------------------------

class TestLifecycleConfig:
    BASE = {
        "session-store": {"type": "memory"},
        "cluster": {
            "members": ["http://a:1", "http://b:2"],
            "self": "http://a:1",
            "replication-factor": 2,
            "l2": {"uri": "redis://localhost:6379/0"},
            "lease-ttl-s": 5,
        },
    }

    def _with(self, **cluster_extra):
        raw = json.loads(json.dumps(self.BASE))
        raw["cluster"].update(cluster_extra)
        return Config.from_dict(raw)

    def test_valid_lifecycle_blocks(self):
        config = self._with(
            drain={"deadline-s": 3, "signal": False},
            repair={"interval-s": 2.5, "max-keys": 16},
            suspect={"enabled": True, "error-rate": 0.4,
                     "p99-factor": 2.0, "min-requests": 4,
                     "peer-failures": 2},
        )
        assert config.cluster.drain.deadline_s == 3
        assert config.cluster.drain.signal is False
        assert config.cluster.repair.interval_s == 2.5
        assert config.cluster.repair.max_keys == 16
        assert config.cluster.suspect.enabled
        assert config.cluster.suspect.error_rate == 0.4

    def test_defaults(self):
        config = self._with()
        assert config.cluster.drain.deadline_s == 10.0
        assert config.cluster.drain.signal is True
        assert config.cluster.repair.interval_s == 0.0
        assert not config.cluster.suspect.enabled

    def test_unknown_keys_fail(self):
        for block in ("drain", "repair", "suspect"):
            with pytest.raises(ConfigError):
                self._with(**{block: {"typo-key": 1}})

    def test_repair_requires_replication(self):
        raw = json.loads(json.dumps(self.BASE))
        raw["cluster"]["replication-factor"] = 1
        raw["cluster"]["repair"] = {"interval-s": 1}
        with pytest.raises(ConfigError):
            Config.from_dict(raw)

    def test_suspect_requires_leases(self):
        raw = json.loads(json.dumps(self.BASE))
        del raw["cluster"]["lease-ttl-s"]
        raw["cluster"]["suspect"] = {"enabled": True}
        with pytest.raises(ConfigError):
            Config.from_dict(raw)

    def test_bad_values_fail(self):
        with pytest.raises(ConfigError):
            self._with(drain={"deadline-s": 0})
        with pytest.raises(ConfigError):
            self._with(drain={"signal": "yes"})
        with pytest.raises(ConfigError):
            self._with(suspect={"enabled": True, "error-rate": 0})
        with pytest.raises(ConfigError):
            self._with(repair={"max-keys": 0})


# ---------------------------------------------------------------------------
# r18 chaos: rolling restart — the zero-5xx planned-leave pin
# ---------------------------------------------------------------------------

WARM_SOURCES = ("hit", "l2-hit", "peer-hit")


class TestRollingRestart:
    @pytest.mark.resilience
    async def test_rolling_restart_zero_5xx_warm_hits(self, tmp_path):
        """Drain each of three replicas in sequence under live
        traffic: zero 5xx anywhere, warm-hit rate >= 0.95 across the
        whole drive (the handoff + join warm-up carrying the hot set
        through every restart — the L2 tile keys are flushed after
        each kill so shared Redis can't mask a lost hot set), and the
        lease/ring view reconverging to three members after every
        step."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=3,
            cluster_extra={
                "lease-ttl-s": 0.6, "replication-factor": 2,
                "drain": {"deadline-s": 5, "signal": False},
            },
        )
        img_path = str(tmp_path / "img.ome.tiff")
        paths = _tile_paths(8)
        statuses = []
        sources = []
        peer_headers = {**AUTH, "X-OMPB-Peer": "ops"}
        try:
            await asyncio.sleep(0.5)  # leases discovered
            etags = {}
            async with ClientSession() as http:
                # warm every path on every replica (RAM + L2 copies)
                for path in paths:
                    for r in replicas:
                        status, _b, h = await _get(http, r.url + path)
                        assert status == 200
                        etags.setdefault(path, h.get("ETag"))
                        assert h.get("ETag") == etags[path]

                async def traffic_round(live):
                    for path in paths:
                        for r in live:
                            status, _b, h = await _get(
                                http, r.url + path
                            )
                            statuses.append(status)
                            sources.append(h.get("X-Cache"))
                            if status == 200:
                                assert h.get("ETag") == etags[path]

                for i in range(3):
                    victim = replicas[i]
                    survivors = [
                        r for j, r in enumerate(replicas) if j != i
                    ]
                    # the draining replica itself keeps serving: the
                    # marker moves ownership, not traffic
                    async def _drain(url):
                        async def _one():
                            async with http.post(
                                url + "/internal/drain?wait=1",
                                headers=peer_headers,
                            ) as r:
                                return r.status, await r.read()
                        return await asyncio.wait_for(_one(), 30.0)

                    drain_task = asyncio.ensure_future(
                        _drain(victim.url)
                    )
                    while not drain_task.done():
                        await traffic_round(survivors)
                        status, _b, _h = await _get(
                            http, victim.url + paths[0]
                        )
                        statuses.append(status)
                        await asyncio.sleep(0.05)
                    status, body = await drain_task
                    assert status == 200
                    drained = json.loads(body)
                    assert drained["state"] == "drained"
                    assert drained["stats"]["handoff"]["pushed"] > 0
                    await victim.kill()
                    # flush the shared tier's tile keys: from here the
                    # handed-off RAM copies are the ONLY warm source
                    # for the victim's keys
                    for key in [
                        k for k in resp.data
                        if k.startswith(b"ompb:tile:")
                    ]:
                        del resp.data[key]
                    for _ in range(3):
                        await traffic_round(survivors)
                    # rolling restart: the replacement boots on the
                    # same identity and warms via the join transfer
                    replicas[i] = await _boot_replica(
                        img_path,
                        [r.url for r in replicas],
                        victim.url,
                        int(victim.url.rsplit(":", 1)[1]),
                        resp.uri,
                        cluster_extra={
                            "lease-ttl-s": 0.6,
                            "replication-factor": 2,
                            "drain": {"deadline-s": 5,
                                      "signal": False},
                        },
                    )
                    deadline = time.monotonic() + 6.0
                    while time.monotonic() < deadline:
                        views = [
                            len(r.app.cache_plane.membership.members)
                            for r in replicas if not r.dead
                        ]
                        if all(v == 3 for v in views):
                            break
                        await traffic_round(survivors)
                        await asyncio.sleep(0.1)
                    assert all(
                        len(r.app.cache_plane.membership.members) == 3
                        for r in replicas if not r.dead
                    )
            # THE pins: a planned leave is not a crash
            assert statuses, "no traffic was driven"
            assert all(s < 500 for s in statuses), (
                f"5xx during rolling restart: "
                f"{[s for s in statuses if s >= 500]}"
            )
            warm = sum(1 for s in sources if s in WARM_SOURCES)
            warm_rate = warm / max(1, len(sources))
            assert warm_rate >= 0.95, (
                f"warm-hit rate {warm_rate:.3f} over {len(sources)} "
                f"requests (sources: {set(sources)})"
            )
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_drain_endpoint_requires_peer_marker(self, tmp_path):
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2, cluster_extra={"lease-ttl-s": 0.6},
        )
        try:
            async with ClientSession() as http:
                async with http.post(
                    replicas[0].url + "/internal/drain"
                ) as r:
                    assert r.status == 403
                async with http.get(
                    replicas[0].url + "/healthz"
                ) as r:
                    health = await r.json()
            assert health["cluster"]["drain"]["state"] == "serving"
            assert health["slo"]["draining"] is False
        finally:
            await cleanup()


# ---------------------------------------------------------------------------
# r18 chaos: anti-entropy repair convergence
# ---------------------------------------------------------------------------

class TestAntiEntropyChaos:
    @pytest.mark.resilience
    async def test_missed_push_repaired_within_one_rotation(
        self, tmp_path
    ):
        """A deliberately-dropped replica push is healed by the
        digest exchange within one rotation over the peers (<= 2
        rounds in a 3-replica fleet), byte-identical; once converged,
        the next round is a checksum-skip costing one digest GET."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=3,
            cluster_extra={
                "lease-ttl-s": 0.6, "replication-factor": 2,
                # the loop cadence is irrelevant here: rounds are
                # driven by hand for determinism
                "repair": {"interval-s": 60, "max-keys": 32},
            },
        )
        try:
            await asyncio.sleep(0.5)
            plane0 = replicas[0].app.cache_plane
            by_url = {r.url: r for r in replicas}

            # a path owned by some replica A with successor B
            target = None
            for path in _tile_paths(16):
                key = _key_for(replicas[0].app, path)
                owners = plane0.ring.owners(key, 2)
                if len(owners) == 2:
                    target = (path, key, owners[0], owners[1])
                    break
            assert target is not None
            path, key, owner_url, succ_url = target
            owner = by_url[owner_url]
            succ = by_url[succ_url]

            # sabotage: the owner's push never leaves the building
            async def lost_push(*a, **k):
                return None

            owner.app.cache_plane._push_replicas = lost_push
            async with ClientSession() as http:
                for _ in range(2):  # second touch crosses the hot bar
                    status, _b, h = await _get(
                        http, owner_url + path
                    )
                    assert status == 200
                    etag = h.get("ETag")
            assert owner.app.result_cache.contains(key)
            assert not succ.app.result_cache.contains(key)

            succ_plane = succ.app.cache_plane
            pulled = 0
            rounds = 0
            for _ in range(2):  # one full rotation over the peers
                rounds += 1
                pulled += await succ_plane.repair_round()
                if succ.app.result_cache.contains(key):
                    break
            assert succ.app.result_cache.contains(key), (
                f"not repaired after {rounds} rounds"
            )
            assert pulled >= 1
            entry = await succ.app.result_cache.get(key)
            assert entry.etag == etag  # byte-identity via validator
            snap = succ_plane.repairer.snapshot()
            assert snap["pulled"] >= 1

            # converged: a full rotation of rounds is digest-GETs only
            before = snap["pulled"]
            for _ in range(2):
                await succ_plane.repair_round()
            snap = succ_plane.repairer.snapshot()
            assert snap["pulled"] == before
            assert snap["skipped_unchanged"] + snap["rounds"] > 0
        finally:
            await cleanup()


# ---------------------------------------------------------------------------
# r18 chaos: replay-proof peer surface
# ---------------------------------------------------------------------------

class TestNonceReplayHTTP:
    @pytest.mark.resilience
    async def test_replayed_signature_403s(self, tmp_path):
        """A captured ``X-OMPB-Sig`` re-presented verbatim fails even
        INSIDE the clock-skew window — the r17 replay hole. Fresh
        signatures for the same request keep working."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2,
            cluster_extra={"lease-ttl-s": 0.6, "secret": "s3"},
        )
        try:
            await asyncio.sleep(0.4)
            url = replicas[0].url
            path_qs = "/internal/purge/1"
            captured = sign("s3", "POST", path_qs,
                            peer="attacker-replay")
            headers = {
                "X-OMPB-Peer": "attacker-replay",
                SIG_HEADER: captured,
            }
            async with ClientSession() as http:
                async with http.post(
                    url + path_qs, headers=headers
                ) as r:
                    assert r.status == 200  # the original lands once
                async with http.post(
                    url + path_qs, headers=headers
                ) as r:
                    assert r.status == 403  # the replay never does
                # a fresh signature (new nonce) still works
                async with http.post(
                    url + path_qs, headers={
                        "X-OMPB-Peer": "attacker-replay",
                        SIG_HEADER: sign(
                            "s3", "POST", path_qs,
                            peer="attacker-replay",
                        ),
                    },
                ) as r:
                    assert r.status == 200
                # replays counted for operators
                async with http.get(url + "/healthz") as r:
                    health = await r.json()
                assert health["cluster"]["nonces"][
                    "replays_rejected"
                ] >= 1
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_v1_signature_403s_over_http(self, tmp_path):
        """An r17-era (nonce-less) signature is dead on arrival: the
        replay closure refuses the whole scheme, not just repeats."""
        import hashlib
        import hmac as hmac_mod

        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2,
            cluster_extra={"lease-ttl-s": 0.6, "secret": "s3"},
        )
        try:
            url = replicas[0].url
            path_qs = "/internal/transfer?limit=4"
            ts = str(int(time.time()))
            message = "\n".join(
                ("GET", path_qs, ts, hashlib.sha256(b"").hexdigest())
            ).encode()
            mac = hmac_mod.new(
                b"s3", message, hashlib.sha256
            ).hexdigest()
            async with ClientSession() as http:
                async with http.get(
                    url + path_qs, headers={
                        "X-OMPB-Peer": "old-replica",
                        SIG_HEADER: f"v1:{ts}:{mac}",
                    },
                ) as r:
                    assert r.status == 403
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_signed_cluster_traffic_unaffected(self, tmp_path):
        """The replay guard never taxes legitimate traffic: a signed
        two-replica cluster replicates, transfers, and peer-serves
        exactly as before (every outbound exchange mints its own
        nonce)."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2,
            cluster_extra={
                "lease-ttl-s": 0.6, "replication-factor": 2,
                "secret": "s3",
            },
        )
        try:
            await asyncio.sleep(0.5)
            paths = _tile_paths(6)
            async with ClientSession() as http:
                for path in paths:
                    for r in replicas:
                        status, _b, _h = await _get(
                            http, r.url + path
                        )
                        assert status == 200
                await asyncio.sleep(0.5)  # pushes drain, signed
            rep = (
                replicas[0].app.cache_plane.replicator.snapshot()[
                    "pushed"
                ]
                + replicas[1].app.cache_plane.replicator.snapshot()[
                    "pushed"
                ]
            )
            assert rep > 0
        finally:
            await cleanup()


# ---------------------------------------------------------------------------
# r18 chaos: quality-based suspicion demotes a sick replica
# ---------------------------------------------------------------------------

class TestQualityDemotionChaos:
    @pytest.mark.resilience
    async def test_error_storm_demotes_then_recovers(self, tmp_path):
        """A replica serving a 5xx storm (but heartbeating fine) is
        demoted off the ring by its peers' quorum within a few brain
        rounds, keeps its lease the whole time, and is restored once
        its signals recover."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=3,
            cluster_extra={
                "lease-ttl-s": 0.6,
                "suspect": {"enabled": True, "min-requests": 8,
                            "error-rate": 0.5},
            },
        )
        try:
            await asyncio.sleep(0.5)
            sick = replicas[2]
            observers = replicas[:2]

            async def error_storm(seconds):
                deadline = time.monotonic() + seconds
                while time.monotonic() < deadline:
                    for _ in range(10):
                        sick.app.quality.note(500, 0.01)
                    await asyncio.sleep(0.1)

            storm = asyncio.ensure_future(error_storm(6.0))
            try:
                deadline = time.monotonic() + 6.0
                while time.monotonic() < deadline:
                    if all(
                        sick.url in r.app.cache_plane.demoted
                        for r in observers
                    ):
                        break
                    await asyncio.sleep(0.1)
            finally:
                storm.cancel()
            for r in observers:
                plane = r.app.cache_plane
                assert sick.url in plane.demoted, (
                    plane.brains.snapshot()
                )
                # demoted = off the RING, not out of the fleet
                assert sick.url not in plane.ring.members
                assert sick.url in plane.membership.members
            # recovery: windows with no errors dissolve the quorum
            deadline = time.monotonic() + 6.0
            while time.monotonic() < deadline:
                if all(
                    sick.url not in r.app.cache_plane.demoted
                    for r in observers
                ):
                    break
                await asyncio.sleep(0.1)
            for r in observers:
                plane = r.app.cache_plane
                assert sick.url not in plane.demoted
                assert sick.url in plane.ring.members
        finally:
            await cleanup()
