"""Pallas fused byteswap+filter kernel vs the numpy reference path.

Runs in interpret mode on the CPU test backend (conftest pins
JAX_PLATFORMS=cpu); the same kernel compiles for TPU in production.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from omero_ms_pixel_buffer_tpu.ops.pallas import filter_tiles, supports
from omero_ms_pixel_buffer_tpu.ops.png import filter_rows_np
from omero_ms_pixel_buffer_tpu.ops.convert import to_big_endian_bytes_np

MODES = ["none", "sub", "up", "average", "paeth"]
DTYPES = [np.uint8, np.int8, np.uint16, np.int16]


def reference(batch: np.ndarray, mode: str) -> np.ndarray:
    out = []
    samples = batch.shape[3] if batch.ndim == 4 else 1
    for tile in batch:
        rows = to_big_endian_bytes_np(tile)
        if rows.ndim == 3:  # (H, W, S*itemsize) -> scanrows
            rows = rows.reshape(rows.shape[0], -1)
        out.append(
            filter_rows_np(rows, samples * tile.dtype.itemsize, mode)
        )
    return np.stack(out)


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("dtype", DTYPES)
def test_matches_numpy_reference(mode, dtype):
    rng = np.random.default_rng(42)
    info = np.iinfo(dtype)
    batch = rng.integers(
        info.min, info.max, (3, 24, 40), dtype=dtype, endpoint=True
    )
    got = np.asarray(filter_tiles(jnp.asarray(batch), mode))
    expect = reference(batch, mode)
    np.testing.assert_array_equal(got, expect)


def test_non_square_and_single_lane():
    rng = np.random.default_rng(0)
    batch = rng.integers(0, 65535, (1, 7, 129), dtype=np.uint16)
    got = np.asarray(filter_tiles(jnp.asarray(batch), "up"))
    np.testing.assert_array_equal(got, reference(batch, "up"))


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("dtype", [np.uint8, np.uint16])
def test_rgb_matches_numpy_reference(mode, dtype):
    rng = np.random.default_rng(7)
    info = np.iinfo(dtype)
    batch = rng.integers(
        info.min, info.max, (2, 16, 24, 3), dtype=dtype, endpoint=True
    )
    got = np.asarray(filter_tiles(jnp.asarray(batch), mode))
    np.testing.assert_array_equal(got, reference(batch, mode))


def test_supports_gate():
    assert supports((512, 512), np.uint16)
    assert supports((256, 256), np.int8)
    assert supports((256, 256), np.uint8, samples=3)  # interleaved RGB
    assert not supports((512, 512), np.uint32)  # 4-byte: XLA path
    assert not supports((256, 256), np.uint8, samples=2)  # gray+alpha
    assert not supports((4096, 4096), np.uint16)  # beyond VMEM blocks
    assert not supports((512, 512), np.uint16, samples=3)  # over budget


def test_unknown_mode_raises():
    with pytest.raises(ValueError):
        filter_tiles(jnp.zeros((1, 8, 8), jnp.uint8), "bogus")


def test_unsupported_shape_raises():
    with pytest.raises(ValueError):
        filter_tiles(jnp.zeros((1, 8, 8), jnp.uint32), "up")
