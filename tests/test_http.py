"""End-to-end HTTP integration: routes, auth, error mapping, headers,
format matrix — the reference's manual-curl verification matrix
(README.md:129-144) as automated tests, against a fake session store +
synthetic fixtures (SURVEY.md §4)."""

import io
import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.io.zarr import write_ngff
from omero_ms_pixel_buffer_tpu.utils.config import Config

rng = np.random.default_rng(3)

IMG = rng.integers(0, 60000, (1, 2, 4, 96, 128), dtype=np.uint16)


@pytest.fixture
def client(tmp_path, loop):
    write_ome_tiff(
        str(tmp_path / "img.ome.tiff"), IMG, tile_size=(64, 64),
        pyramid_levels=2,
    )
    zarr_img = rng.integers(0, 255, (1, 1, 1, 64, 64), dtype=np.uint8)
    write_ngff(str(tmp_path / "img.zarr"), zarr_img)
    registry = ImageRegistry()
    registry.add(1, str(tmp_path / "img.ome.tiff"))
    registry.add(2, str(tmp_path / "img.zarr"), type="zarr")
    store = MemorySessionStore({"cookie-1": "omero-key-1"})
    config = Config.from_dict(
        {"session-store": {"type": "memory"},
         "backend": {"batching": {"coalesce-window-ms": 1.0}}}
    )
    app_obj = PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=store,
    )
    client = TestClient(TestServer(app_obj.make_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield client
    loop.run_until_complete(client.close())


AUTH = {"Cookie": "sessionid=cookie-1"}


class TestRoutes:
    async def test_options_discovery(self, client):
        resp = await client.request("OPTIONS", "/")
        assert resp.status == 200
        body = await resp.json()
        assert body["provider"] == "PixelBufferMicroservice"
        assert "version" in body and body["features"] == []

    async def test_metrics_unauthenticated(self, client):
        resp = await client.get("/metrics")
        assert resp.status == 200
        text = await resp.text()
        assert "# TYPE" in text

    async def test_raw_tile(self, client):
        resp = await client.get(
            "/tile/1/0/0/0?x=8&y=16&w=32&h=24", headers=AUTH
        )
        assert resp.status == 200
        assert resp.headers["Content-Type"] == "application/octet-stream"
        body = await resp.read()
        assert resp.headers["Content-Length"] == str(len(body))
        assert (
            resp.headers["Content-Disposition"]
            == 'attachment; filename="image1_z0_c0_t0_x8_y16_w32_h24.bin"'
        )
        # raw bytes are big-endian uint16
        tile = np.frombuffer(body, dtype=">u2").reshape(24, 32)
        np.testing.assert_array_equal(
            tile.astype(np.uint16), IMG[0, 0, 0, 16:40, 8:40]
        )

    async def test_png_tile(self, client):
        resp = await client.get(
            "/tile/1/1/1/0?x=0&y=0&w=64&h=64&format=png", headers=AUTH
        )
        assert resp.status == 200
        assert resp.headers["Content-Type"] == "image/png"
        body = await resp.read()
        decoded = np.array(Image.open(io.BytesIO(body)))
        np.testing.assert_array_equal(
            decoded.astype(np.uint16), IMG[0, 1, 1, :64, :64]
        )

    async def test_tif_tile(self, client):
        resp = await client.get(
            "/tile/1/0/0/0?w=48&h=32&format=tif", headers=AUTH
        )
        assert resp.status == 200
        assert resp.headers["Content-Type"] == "image/tiff"
        body = await resp.read()
        decoded = np.array(Image.open(io.BytesIO(body)))
        np.testing.assert_array_equal(
            decoded.astype(np.uint16), IMG[0, 0, 0, :32, :48]
        )
        assert resp.headers["Content-Disposition"].endswith('.tif"')

    async def test_wh_zero_defaults_full_plane(self, client):
        resp = await client.get("/tile/2/0/0/0", headers=AUTH)
        assert resp.status == 200
        body = await resp.read()
        assert len(body) == 64 * 64  # uint8 full plane
        assert "w64_h64" in resp.headers["Content-Disposition"]

    async def test_resolution_level(self, client):
        resp = await client.get(
            "/tile/1/0/0/0?resolution=1&w=64&h=48", headers=AUTH
        )
        assert resp.status == 200
        tile = np.frombuffer(await resp.read(), dtype=">u2").reshape(48, 64)
        np.testing.assert_array_equal(
            tile.astype(np.uint16), IMG[0, 0, 0, ::2, ::2][:48, :64]
        )


class TestErrors:
    async def test_no_cookie_403(self, client):
        resp = await client.get("/tile/1/0/0/0")
        assert resp.status == 403

    async def test_unknown_session_403(self, client):
        resp = await client.get(
            "/tile/1/0/0/0", headers={"Cookie": "sessionid=nope"}
        )
        assert resp.status == 403

    async def test_bad_param_400(self, client):
        resp = await client.get("/tile/abc/0/0/0", headers=AUTH)
        assert resp.status == 400
        assert "abc" in await resp.text()

    async def test_unknown_image_404(self, client):
        resp = await client.get("/tile/99/0/0/0", headers=AUTH)
        assert resp.status == 404

    async def test_unknown_format_404(self, client):
        resp = await client.get(
            "/tile/1/0/0/0?format=bmp&w=8&h=8", headers=AUTH
        )
        assert resp.status == 404

    async def test_out_of_bounds_404(self, client):
        resp = await client.get(
            "/tile/1/0/0/0?x=120&y=90&w=64&h=64", headers=AUTH
        )
        assert resp.status == 404

    async def test_bad_z_404(self, client):
        resp = await client.get("/tile/1/9/0/0?w=8&h=8", headers=AUTH)
        assert resp.status == 404

    async def test_bad_resolution_404(self, client):
        resp = await client.get(
            "/tile/1/0/0/0?resolution=7&w=8&h=8", headers=AUTH
        )
        assert resp.status == 404


class TestBatching:
    async def test_concurrent_requests_coalesce(self, client):
        import asyncio

        async def fetch(z, c):
            resp = await client.get(
                f"/tile/1/{z}/{c}/0?w=64&h=64&format=png", headers=AUTH
            )
            assert resp.status == 200
            return np.array(Image.open(io.BytesIO(await resp.read())))

        results = await asyncio.gather(
            *(fetch(z, c) for z in range(4) for c in range(2))
        )
        i = 0
        for z in range(4):
            for c in range(2):
                np.testing.assert_array_equal(
                    results[i].astype(np.uint16), IMG[0, c, z, :64, :64]
                )
                i += 1

    async def test_mixed_formats_in_one_burst(self, client):
        import asyncio

        async def fetch(fmt):
            url = f"/tile/1/0/0/0?w=32&h=32"
            if fmt:
                url += f"&format={fmt}"
            resp = await client.get(url, headers=AUTH)
            return resp.status, await resp.read()

        results = await asyncio.gather(
            *(fetch(f) for f in [None, "png", "tif", None, "png"])
        )
        for status, _ in results:
            assert status == 200


class TestRgbImage:
    """RGB (SamplesPerPixel=3) images through the full HTTP surface."""

    @pytest.fixture
    def rgb_client(self, tmp_path, loop):
        rgb = rng.integers(0, 255, (1, 1, 1, 48, 56, 3), dtype=np.uint8)
        write_ome_tiff(
            str(tmp_path / "rgb.ome.tiff"), rgb, tile_size=(32, 32)
        )
        registry = ImageRegistry()
        registry.add(1, str(tmp_path / "rgb.ome.tiff"))
        store = MemorySessionStore({"cookie-1": "omero-key-1"})
        config = Config.from_dict({"session-store": {"type": "memory"}})
        app_obj = PixelBufferApp(
            config, pixels_service=PixelsService(registry),
            session_store=store,
        )
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        loop.run_until_complete(client.start_server())
        yield client, rgb[0, 0, 0]
        loop.run_until_complete(client.close())

    def test_rgb_channels_served_separately(self, rgb_client, loop):
        """OMERO semantics: an RGB image is SizeC=3; channel c serves
        that sample as a grayscale tile (viewers compose client-side)."""
        client, truth = rgb_client

        async def run():
            for c in range(3):
                r = await client.get(
                    f"/tile/1/0/{c}/0?x=8&y=4&w=32&h=24&format=png",
                    headers=AUTH,
                )
                assert r.status == 200
                png = np.array(Image.open(io.BytesIO(await r.read())))
                np.testing.assert_array_equal(png, truth[4:28, 8:40, c])
            r2 = await client.get(
                "/tile/1/0/2/0?x=0&y=0&w=56&h=48&format=tif",
                headers=AUTH,
            )
            assert r2.status == 200
            tif = np.array(Image.open(io.BytesIO(await r2.read())))
            np.testing.assert_array_equal(tif, truth[:, :, 2])
            # channel out of range -> 404, like any bad coordinate
            r3 = await client.get(
                "/tile/1/0/3/0?w=8&h=8", headers=AUTH
            )
            assert r3.status == 404

        loop.run_until_complete(run())


class TestFloatImage:
    """float32 pixels: raw and TIFF serve; PNG has no float -> 404
    (the reference's encode-failure -> null -> 404 path)."""

    @pytest.fixture
    def float_client(self, tmp_path, loop):
        data = rng.normal(0, 1, (1, 1, 1, 32, 40)).astype(np.float32)
        write_ome_tiff(str(tmp_path / "f.ome.tiff"), data)
        registry = ImageRegistry()
        registry.add(1, str(tmp_path / "f.ome.tiff"))
        store = MemorySessionStore({"cookie-1": "omero-key-1"})
        config = Config.from_dict({"session-store": {"type": "memory"}})
        app_obj = PixelBufferApp(
            config, pixels_service=PixelsService(registry),
            session_store=store,
        )
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        loop.run_until_complete(client.start_server())
        yield client, data[0, 0, 0]
        loop.run_until_complete(client.close())

    def test_float_formats(self, float_client, loop):
        client, truth = float_client

        async def run():
            r = await client.get("/tile/1/0/0/0?w=0&h=0", headers=AUTH)
            assert r.status == 200
            raw = np.frombuffer(await r.read(), dtype=">f4").reshape(32, 40)
            np.testing.assert_array_equal(
                raw.astype(np.float32), truth
            )
            r2 = await client.get(
                "/tile/1/0/0/0?w=0&h=0&format=tif", headers=AUTH
            )
            assert r2.status == 200
            tif = np.array(Image.open(io.BytesIO(await r2.read())))
            np.testing.assert_array_equal(tif, truth)
            r3 = await client.get(
                "/tile/1/0/0/0?w=8&h=8&format=png", headers=AUTH
            )
            assert r3.status == 404  # no float PNG

        loop.run_until_complete(run())


class TestGuardsAndFuzz:
    def test_oversized_tile_404(self, tmp_path, loop):
        data = np.zeros((1, 1, 1, 64, 64), np.uint16)
        write_ome_tiff(str(tmp_path / "g.ome.tiff"), data)
        registry = ImageRegistry()
        registry.add(1, str(tmp_path / "g.ome.tiff"))
        store = MemorySessionStore({"cookie-1": "omero-key-1"})
        config = Config.from_dict(
            {"session-store": {"type": "memory"},
             "backend": {"max-tile-mb": 0}}  # disabled -> full plane OK
        )
        assert config.backend.max_tile_mb == 0
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        pipe = TilePipeline(
            PixelsService(registry), engine="host", max_tile_bytes=1024
        )
        from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

        big = TileCtx(
            image_id=1, z=0, c=0, t=0, region=RegionDef(0, 0, 0, 0),
            format=None, omero_session_key="k",
        )  # full plane = 8 KiB > 1 KiB guard
        assert pipe.handle(big) is None  # -> 404 via broad catch
        small = TileCtx(
            image_id=1, z=0, c=0, t=0, region=RegionDef(0, 0, 16, 16),
            format=None, omero_session_key="k",
        )
        assert pipe.handle(small) is not None

    def test_param_fuzz_never_500(self, client, loop):
        """Garbage params must map to 4xx/404, never 500."""
        cases = [
            "/tile/1/0/0/0?x=-5&y=0&w=8&h=8",
            "/tile/1/0/0/0?w=1e9&h=2",
            "/tile/1/0/0/0?resolution=-1&w=8&h=8",
            "/tile/1/0/0/0?resolution=99&w=8&h=8",
            "/tile/1/zz/0/0?w=8&h=8",
            "/tile/1/0/0/0?w=8&h=8&format=bmp",
            "/tile/99999999999999999999/0/0/0?w=8&h=8",
            "/tile/1/0/0/0?x=999999&y=999999&w=8&h=8",
        ]

        async def run():
            for path in cases:
                r = await client.get(path, headers=AUTH)
                assert 400 <= r.status < 500, (path, r.status)

        loop.run_until_complete(run())
