"""Flight-recorder observability plane (obs/): stage-completeness
matrix over real HTTP serves, tail-sampler keep/drop decisions,
requester<->owner trace continuity over a loopback two-replica
cluster, OpenMetrics exemplar exposition validity, /debug/requests
bounds + gating, and the dead-Zipkin chaos lane."""

import asyncio
import re
import socket
import time

import numpy as np
import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.obs import FlightRecorder, SliLayer
from omero_ms_pixel_buffer_tpu.obs.recorder import STAGES
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError
from omero_ms_pixel_buffer_tpu.utils.metrics import REGISTRY, Registry
from omero_ms_pixel_buffer_tpu.utils.tracing import (
    TRACER,
    ZipkinReporter,
    configure as configure_tracing,
)

rng = np.random.default_rng(11)
IMG = rng.integers(0, 60000, (1, 2, 2, 128, 128), dtype=np.uint16)
AUTH = {"Cookie": "sessionid=cookie-1"}


def _make_app(tmp_path, obs_overrides=None, extra=None):
    img = str(tmp_path / "img.ome.tiff")
    write_ome_tiff(img, IMG, tile_size=(64, 64), pyramid_levels=2)
    registry = ImageRegistry()
    registry.add(1, img)
    raw = {
        "session-store": {"type": "memory"},
        "backend": {"batching": {"coalesce-window-ms": 1.0}},
        "cache": {"prefetch": {"enabled": False}},
        "obs": {"head-sample-rate": 1.0, **(obs_overrides or {})},
    }
    if extra:
        for k, v in extra.items():
            if isinstance(v, dict):
                raw.setdefault(k, {}).update(v)
            else:
                raw[k] = v
    config = Config.from_dict(raw)
    return PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=MemorySessionStore({"cookie-1": "omero-key-1"}),
    )


@pytest.fixture
def served(tmp_path, loop):
    """(client, app_obj) with everything kept (head-sample-rate 1)."""
    app_obj = _make_app(tmp_path)
    client = TestClient(TestServer(app_obj.make_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield client, app_obj
    loop.run_until_complete(client.close())


# ---------------------------------------------------------------------------
# recorder unit behavior
# ---------------------------------------------------------------------------


class TestRecorder:
    def test_fixed_slots_and_accumulation(self):
        rec = FlightRecorder(head_sample_rate=1.0).start("/tile/1")
        rec.stamp("read", 0.010)
        rec.stamp("read", 0.005)
        rec.stamp("encode", 0.002)
        touched = dict(
            (name, dur) for name, _, dur in rec.touched()
        )
        assert touched["read"] == pytest.approx(0.015)
        assert touched["encode"] == pytest.approx(0.002)
        assert set(touched) <= set(STAGES)

    def test_unknown_stage_refused(self):
        rec = FlightRecorder().start("/tile/1")
        with pytest.raises(KeyError):
            rec.stamp("not-a-stage", 0.1)

    def test_wide_event_sums_within_slack(self):
        recorder = FlightRecorder(head_sample_rate=1.0)
        rec = recorder.start("/tile/1")
        with rec.stage("resolve"):
            time.sleep(0.01)
        with rec.stage("read"):
            time.sleep(0.02)
        recorder.complete(rec, 200)
        event = recorder.events()[0]
        attributed = sum(event["stages_ms"].values())
        assert attributed <= event["total_ms"] + 1.0
        assert event["total_ms"] == pytest.approx(
            attributed + event["unattributed_ms"], abs=0.1
        )
        assert event["stages_ms"]["read"] >= 15.0

    def test_complete_is_idempotent(self):
        recorder = FlightRecorder(head_sample_rate=1.0)
        rec = recorder.start("/tile/1")
        assert recorder.complete(rec, 200)
        assert not recorder.complete(rec, 500)
        assert len(recorder.events()) == 1

    def test_disabled_recorder_mints_nothing(self):
        recorder = FlightRecorder(enabled=False)
        assert recorder.start("/tile/1") is None
        assert recorder.complete(None, 200) is False


class TestTailSampler:
    def _one(self, recorder, status=200, tags=None, faults=(),
             slow=False):
        rec = recorder.start("/tile/1")
        for k, v in (tags or {}).items():
            rec.tag(k, v)
        for point in faults:
            rec.note_fault(point)
        if slow:
            rec.t0 -= 10.0  # fake a 10 s request
        recorder.complete(rec, status)
        return rec

    def test_errors_always_kept(self):
        recorder = FlightRecorder(head_sample_rate=0.0)
        for status, outcome in (
            # a bare 503 is a dependency that could not answer; only
            # the scheduler/door shed tag makes it a "shed"
            (500, "error"), (503, "unavailable"), (504, "timeout"),
        ):
            rec = self._one(recorder, status=status)
            assert rec.kept and rec.keep_reason == "error"
            assert rec.outcome == outcome
        rec = self._one(
            recorder, status=503, tags={"shed_at": "queue"}
        )
        assert rec.kept and rec.outcome == "shed"

    def test_degraded_kept(self):
        recorder = FlightRecorder(head_sample_rate=0.0)
        rec = self._one(recorder, tags={"degraded": 1})
        assert rec.kept and rec.keep_reason == "degraded"
        assert rec.outcome == "degraded"

    def test_slow_kept(self):
        recorder = FlightRecorder(
            head_sample_rate=0.0, slow_threshold_s=0.5
        )
        rec = self._one(recorder, slow=True)
        assert rec.kept and rec.keep_reason == "slow"

    def test_fault_kept(self):
        recorder = FlightRecorder(head_sample_rate=0.0)
        rec = self._one(recorder, faults=["io.range-get"])
        assert rec.kept and rec.keep_reason == "fault"
        assert recorder.events()[0]["faults"] == ["io.range-get"]

    def test_healthy_fast_dropped_at_rate_zero(self):
        recorder = FlightRecorder(head_sample_rate=0.0)
        rec = self._one(recorder)
        assert not rec.kept
        assert recorder.events() == []
        assert recorder.snapshot()["dropped"] == 1

    def test_head_sampling_deterministic_per_trace_id(self):
        """The SAME trace id keeps (or drops) on every recorder — the
        cross-replica whole-trace property."""
        a = FlightRecorder(head_sample_rate=0.3)
        b = FlightRecorder(head_sample_rate=0.3)
        decisions = []
        for i in range(64):
            ra = a.start("/tile/1")
            rb = b.start("/tile/1", trace_id=ra.trace_id)
            a.complete(ra, 200)
            b.complete(rb, 200)
            assert ra.kept == rb.kept
            decisions.append(ra.kept)
        assert any(decisions) and not all(decisions)

    def test_ring_bounded(self):
        recorder = FlightRecorder(head_sample_rate=1.0, ring_size=4)
        for _ in range(10):
            self._one(recorder)
        assert len(recorder.events()) == 4
        assert recorder.snapshot()["kept"] == 10


class TestSli:
    def test_burn_rate_math(self):
        clock = [1000.0]
        sli = SliLayer(budget_s=0.3, clock=lambda: clock[0])
        # 90 good + 10 bad interactive -> bad_frac 0.1 -> burn 10.0
        # (bad via 5 errors + 5 over-budget serves: both count)
        for i in range(100):
            sli.record(
                "interactive",
                0.5 if 5 <= i < 10 else 0.01,
                error=i < 5,
            )
        rates = sli.burn_rates()
        assert rates["5m"]["interactive"] == pytest.approx(10.0)
        assert rates["1h"]["interactive"] == pytest.approx(10.0)
        assert rates["5m"]["bulk"] == 0.0  # no data != incident
        # outside the 5m window the short-window burn clears
        clock[0] += 400.0
        assert sli.burn_rates()["5m"]["interactive"] == 0.0
        assert sli.burn_rates()["1h"]["interactive"] == pytest.approx(10.0)

    def test_unknown_class_folds_to_interactive(self):
        sli = SliLayer(budget_s=0.3)
        sli.record("martian", 0.01)
        assert sli.snapshot()["total"]["interactive"] == 1

    def test_client_errors_never_dilute_the_sli(self):
        """Review fix: fast 4xx refusals (scanner 403s, bad params)
        stay OUT of the good/total ratio — they'd read a real latency
        incident down to 'sustainable'."""
        recorder = FlightRecorder(
            head_sample_rate=0.0, slow_threshold_s=0.3,
            sli=SliLayer(budget_s=0.3),
        )
        for status in (403, 404, 400):
            recorder.complete(recorder.start("/tile/1"), status)
        assert recorder.sli.snapshot()["total"]["interactive"] == 0
        recorder.complete(recorder.start("/tile/1"), 200)
        recorder.complete(recorder.start("/tile/1"), 503)
        totals = recorder.sli.snapshot()
        assert totals["total"]["interactive"] == 2
        assert totals["good"]["interactive"] == 1


# ---------------------------------------------------------------------------
# OpenMetrics exposition + exemplars
# ---------------------------------------------------------------------------

_OM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9][0-9.e+-]*"
    r"( # \{[^{}]*\} -?[0-9][0-9.e+-]* [0-9][0-9.]*)?$"
)


def _validate_openmetrics(text: str) -> None:
    """A hand-rolled validator for the OpenMetrics subset we emit (no
    prometheus_client in the image): EOF terminator, line grammar,
    counter-family naming, exemplars only on histogram buckets."""
    lines = text.strip().split("\n")
    assert lines[-1] == "# EOF"
    families = {}
    for line in lines[:-1]:
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            assert len(parts) >= 3, line
            if parts[1] == "TYPE":
                families[parts[2]] = parts[3]
            continue
        assert not line.startswith("#"), f"stray comment: {line}"
        assert _OM_SAMPLE.match(line), f"bad sample line: {line}"
        name = re.split(r"[{ ]", line, 1)[0]
        if " # {" in line:
            assert name.endswith("_bucket"), (
                f"exemplar outside a histogram bucket: {line}"
            )
            assert 'le="' in line
    # counter families must not end in _total; their samples must
    for fam, kind in families.items():
        if kind == "counter":
            assert not fam.endswith("_total"), fam
    for line in lines[:-1]:
        if line.startswith("#"):
            continue
        name = re.split(r"[{ ]", line, 1)[0]
        for fam, kind in families.items():
            if kind == "counter" and name == fam + "_total":
                break


class TestOpenMetrics:
    def test_exemplar_only_in_openmetrics(self):
        r = Registry()
        h = r.histogram("obs_t_seconds", "t")
        h.observe(0.03, exemplar="deadbeef")
        classic = r.exposition()
        om = r.exposition(openmetrics=True)
        assert "deadbeef" not in classic
        assert "# EOF" not in classic
        assert '# {trace_id="deadbeef"} 0.03' in om
        assert om.strip().endswith("# EOF")

    def test_last_exemplar_wins_per_bucket(self):
        r = Registry()
        h = r.histogram("obs_t2_seconds", "t")
        h.observe(0.03, exemplar="first")
        h.observe(0.04, exemplar="second")  # same 0.05 bucket
        om = r.exposition(openmetrics=True)
        assert "first" not in om and "second" in om

    def test_counter_family_naming(self):
        r = Registry()
        r.counter("foo_total", "f").inc(kind="x")
        om = r.exposition(openmetrics=True)
        assert "# TYPE foo counter" in om
        assert 'foo_total{kind="x"} 1.0' in om

    def test_process_registry_validates(self):
        # the REAL process registry, with whatever every suite already
        # observed — the exposition itself must be valid OpenMetrics
        _validate_openmetrics(REGISTRY.exposition(openmetrics=True))

    def test_classic_exposition_unchanged_shape(self):
        text = REGISTRY.exposition()
        assert "# EOF" not in text and " # {" not in text


# ---------------------------------------------------------------------------
# stage completeness over real HTTP serves
# ---------------------------------------------------------------------------


class TestStageCompleteness:
    async def test_miss_stamps_full_pipeline(self, served):
        client, app_obj = served
        resp = await client.get(
            "/tile/1/0/0/0?x=0&y=0&w=64&h=64&format=png", headers=AUTH
        )
        assert resp.status == 200
        event = app_obj.recorder.events()[0]
        stages = event["stages_ms"]
        for stage in ("auth", "cache_probe", "batch_wait", "resolve",
                      "read", "encode", "frame"):
            assert stage in stages, f"missing {stage}: {stages}"
        assert event["outcome"] == "ok"
        assert event["tags"]["priority"] == "interactive"
        assert event["tags"]["cache"] == "miss"
        assert sum(stages.values()) <= event["total_ms"] + 1.0

    async def test_hit_stamps_probe_and_keeps_provenance(self, served):
        client, app_obj = served
        url = "/tile/1/0/0/0?x=0&y=0&w=64&h=64&format=png"
        await client.get(url, headers=AUTH)
        resp = await client.get(url, headers=AUTH)
        assert resp.status == 200
        assert resp.headers["X-Cache"] == "hit"
        event = app_obj.recorder.events()[0]
        assert event["tags"]["cache"] == "hit"
        assert "cache_probe" in event["stages_ms"]
        # a hit never runs the pipeline
        assert "read" not in event["stages_ms"]

    async def test_404_and_403_complete_records(self, served):
        client, app_obj = served
        resp = await client.get(
            "/tile/999/0/0/0?w=8&h=8", headers=AUTH
        )
        assert resp.status == 404
        assert app_obj.recorder.events()[0]["outcome"] == "client_error"
        resp = await client.get("/tile/1/0/0/0?w=8&h=8")  # no cookie
        assert resp.status == 403
        assert app_obj.recorder.events()[0]["status"] == 403

    async def test_router_404_records_client_status(self, served):
        """Review fix: an unroutable serving path (aiohttp raises
        HTTPNotFound before any handler) must record 404, not 500 —
        scanner noise is a client outcome, never SLI error budget."""
        client, app_obj = served
        resp = await client.get("/tile/1/0/0", headers=AUTH)  # 3 segs
        # the OPTIONS discovery catch-all claims every path, so an
        # unroutable GET surfaces as 405 — still a router-raised
        # HTTPException, still a client outcome
        assert resp.status == 405
        event = app_obj.recorder.events()[0]
        assert event["status"] == 405
        assert event["outcome"] == "client_error"

    async def test_malformed_peer_trace_header_ignored(self, served):
        """Review fix: a non-hex forwarded trace id is refused at
        adoption (a fresh trace is minted) instead of poisoning the
        sampler hash or the exposition."""
        client, app_obj = served
        resp = await client.get(
            "/tile/1/0/0/0?w=8&h=8",
            headers={
                **AUTH,
                "X-OMPB-Peer": "http://evil",
                "X-OMPB-Trace-Id": "not-hex-at-all",
            },
        )
        assert resp.status == 200
        event = app_obj.recorder.events()[0]
        assert event["trace_id"] != "not-hex-at-all"
        assert len(event["trace_id"]) == 32

    async def test_live_root_span_carries_record_span_id(self, served):
        """Review fix: with live tracing on, the exported root span
        must carry the record's span id — it is what the peer hop
        propagates as the owner's parent."""
        client, app_obj = served

        class FakeReporter:
            def __init__(self):
                self.spans = []

            def report(self, span):
                self.spans.append(span)

        fake = FakeReporter()
        old_rep, old_en = TRACER.reporter, TRACER.enabled
        TRACER.reporter, TRACER.enabled = fake, True
        try:
            resp = await client.get(
                "/tile/1/0/1/0?w=8&h=8", headers=AUTH
            )
            assert resp.status == 200
            event = app_obj.recorder.events()[0]
            roots = [
                s for s in fake.spans if s.name.startswith("http:")
            ]
            assert roots and roots[-1].span_id == event["span_id"]
            assert roots[-1].trace_id == event["trace_id"]
        finally:
            TRACER.reporter, TRACER.enabled = old_rep, old_en

    def test_deferred_exemplar_installs_only_when_kept(self):
        """Review fix: deep-site exemplars (queue wait, io fetch,
        device stages) observe mid-request — the trace id attaches at
        completion, only for kept traces; a late note after a kept
        completion attaches immediately."""
        from omero_ms_pixel_buffer_tpu.obs.recorder import (
            defer_exemplar,
            record_scope,
        )

        reg = Registry()
        hist = reg.histogram("deep_seconds", "t")
        recorder = FlightRecorder(head_sample_rate=0.0)
        # dropped record: exemplar never lands
        dropped = recorder.start("/tile/1")
        with record_scope(dropped):
            hist.observe(0.02)
            defer_exemplar(hist, 0.02)
        recorder.complete(dropped, 200)
        assert " # {" not in reg.exposition(openmetrics=True)
        # kept record: exemplar lands at completion
        kept = recorder.start("/tile/1")
        with record_scope(kept):
            hist.observe(0.02)
            defer_exemplar(hist, 0.02)
        recorder.complete(kept, 503)  # force-kept
        om = reg.exposition(openmetrics=True)
        assert kept.trace_id in om and dropped.trace_id not in om
        # late note (device readback after completion): kept record
        # attaches immediately, dropped record never
        hist.observe(0.02)
        with record_scope(kept):
            defer_exemplar(hist, 0.8)  # unobserved series: no-op
            defer_exemplar(hist, 0.02)
        with record_scope(dropped):
            defer_exemplar(hist, 0.02)
        assert kept.trace_id in reg.exposition(openmetrics=True)

    def test_dropped_record_leaves_no_exemplar(self):
        """Review fix: a dropped record's trace id must not become a
        bucket exemplar — the /debug ring could not answer the
        pivot."""
        recorder = FlightRecorder(head_sample_rate=0.0)
        dropped = recorder.start("/tile/1")
        with dropped.stage("read"):
            pass
        recorder.complete(dropped, 200)
        kept = recorder.start("/tile/1")
        kept.tag("degraded", 1)
        with kept.stage("read"):
            pass
        recorder.complete(kept, 200)
        om = REGISTRY.exposition(openmetrics=True)
        assert dropped.trace_id not in om
        assert kept.trace_id in om

    async def test_504_kept_even_unsampled(self, tmp_path, loop):
        app_obj = _make_app(
            tmp_path,
            obs_overrides={"head-sample-rate": 0.0},
            extra={"resilience": {"request-budget-ms": 1}},
        )
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        await client.start_server()
        try:
            resp = await client.get(
                "/tile/1/0/0/0?w=64&h=64", headers=AUTH
            )
            assert resp.status == 504
            event = app_obj.recorder.events()[0]
            assert event["outcome"] == "timeout"
            assert event["kept_reason"] == "error"
        finally:
            await client.close()

    async def test_door_shed_kept_even_unsampled(self, tmp_path, loop):
        app_obj = _make_app(
            tmp_path,
            obs_overrides={"head-sample-rate": 0.0},
            extra={
                "slo": {"queue-size": 0},
                "resilience": {"admission": {"max-inflight": 1}},
            },
        )
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        await client.start_server()
        try:
            assert app_obj.admission.try_slot()  # saturate the gate
            resp = await client.get(
                "/tile/1/0/0/0?w=64&h=64", headers=AUTH
            )
            assert resp.status == 503
            event = app_obj.recorder.events()[0]
            assert event["outcome"] == "shed"
            assert event["tags"]["shed_at"] == "door"
            assert "door" in event["stages_ms"]
        finally:
            app_obj.admission.release()
            await client.close()

    async def test_stage_metrics_independent_of_tracing(self, served):
        """Satellite: the KNOWN_GAPS closure — tracing is disabled in
        this app (the default), yet stage histograms populate."""
        client, app_obj = served
        assert not app_obj.config.http_tracing_enabled
        await client.get(
            "/tile/1/0/0/0?x=64&y=64&w=64&h=64&format=png",
            headers=AUTH,
        )
        text = (await (await client.get("/metrics")).text())
        m = re.search(
            r'request_stage_seconds_count\{stage="resolve"\} (\d+)',
            text,
        )
        assert m and int(m.group(1)) > 0
        assert "http_request_seconds" in text

    async def test_exemplar_carries_ring_trace_id(self, served):
        client, app_obj = served
        await client.get(
            "/tile/1/1/0/0?x=0&y=0&w=64&h=64&format=png", headers=AUTH
        )
        trace_ids = {e["trace_id"] for e in app_obj.recorder.events()}
        resp = await client.get(
            "/metrics",
            headers={"Accept": "application/openmetrics-text"},
        )
        assert "openmetrics" in resp.headers["Content-Type"]
        text = await resp.text()
        _validate_openmetrics(text)
        exemplar_tids = set(
            re.findall(r'# \{trace_id="([0-9a-f]{32})"\}', text)
        )
        assert trace_ids & exemplar_tids

    async def test_healthz_obs_and_burn_rates(self, served):
        client, app_obj = served
        await client.get("/tile/1/0/0/0?w=8&h=8", headers=AUTH)
        body = await (await client.get("/healthz")).json()
        assert body["obs"]["enabled"] is True
        assert body["obs"]["kept"] >= 1
        sli = body["obs"]["sli"]
        assert sli["total"]["interactive"] >= 1
        assert set(sli["burn_rates"]) == {"5m", "30m", "1h"}


# ---------------------------------------------------------------------------
# /debug/requests surface
# ---------------------------------------------------------------------------


class TestDebugSurface:
    async def test_session_exempt_and_bounded(self, tmp_path, loop):
        app_obj = _make_app(tmp_path, obs_overrides={"ring-size": 4})
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        await client.start_server()
        try:
            for i in range(7):
                await client.get(
                    f"/tile/1/0/0/0?x={64 * (i % 2)}&y=0&w=64&h=64"
                    f"&format=png&resolution={i % 2}",
                    headers=AUTH,
                )
            # no cookie: the surface is session-exempt by design
            resp = await client.get("/debug/requests")
            assert resp.status == 200
            body = await resp.json()
            assert body["count"] <= 4
            assert body["ring_size"] == 4
            assert body["kept"] >= 4
            resp = await client.get("/debug/requests?limit=2")
            assert (await resp.json())["count"] == 2
            resp = await client.get("/debug/requests?limit=zebra")
            assert resp.status == 400
        finally:
            await client.close()

    async def test_detail_by_trace_id(self, served):
        client, app_obj = served
        await client.get("/tile/1/0/0/0?w=8&h=8", headers=AUTH)
        tid = app_obj.recorder.events()[0]["trace_id"]
        body = await (
            await client.get(f"/debug/requests/{tid}")
        ).json()
        assert body["trace_id"] == tid
        assert body["events"][0]["trace_id"] == tid
        resp = await client.get("/debug/requests/" + "0" * 32)
        assert resp.status == 404

    async def test_disabled_obs_unmounts_surface(self, tmp_path, loop):
        app_obj = _make_app(
            tmp_path, obs_overrides={"enabled": False}
        )
        assert app_obj.recorder is None
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        await client.start_server()
        try:
            resp = await client.get(
                "/tile/1/0/0/0?w=8&h=8", headers=AUTH
            )
            assert resp.status == 200  # serving unaffected
            # unmounted: no GET route (405 comes from the OPTIONS
            # discovery catch-all claiming the path for OPTIONS only)
            resp = await client.get("/debug/requests")
            assert resp.status in (404, 405)
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# cross-replica trace continuity (loopback two-replica cluster)
# ---------------------------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _make_cluster(tmp_path):
    img = str(tmp_path / "img.ome.tiff")
    write_ome_tiff(img, IMG, tile_size=(64, 64), pyramid_levels=2)
    ports = [_free_port() for _ in range(2)]
    members = [f"http://127.0.0.1:{p}" for p in ports]
    apps = []
    for i, port in enumerate(ports):
        registry = ImageRegistry()
        registry.add(1, img)
        config = Config.from_dict({
            "session-store": {"type": "memory"},
            "backend": {"batching": {"coalesce-window-ms": 1.0}},
            "cache": {"prefetch": {"enabled": False}},
            "obs": {"head-sample-rate": 1.0},
            "cluster": {
                "members": members,
                "self": members[i],
                "peer-timeout-ms": 2000,
            },
        })
        app_obj = PixelBufferApp(
            config,
            pixels_service=PixelsService(registry),
            session_store=MemorySessionStore({"cookie-1": "omero-key-1"}),
        )
        runner = web.AppRunner(app_obj.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", port)
        await site.start()
        apps.append((app_obj, runner, members[i]))

    async def cleanup():
        for _, runner, _ in apps:
            await runner.cleanup()

    return apps, cleanup


class TestPeerTraceContinuity:
    async def test_one_trace_spans_requester_and_owner(self, tmp_path):
        """The tentpole's cluster half: a peer-served tile produces
        ONE trace id, kept in BOTH replicas' rings — the requester's
        event carries the peer stage + owner tag, the owner's event
        carries the peer origin."""
        apps, cleanup = await _make_cluster(tmp_path)
        try:
            import aiohttp

            from omero_ms_pixel_buffer_tpu.tile_ctx import TileCtx

            requester, _, requester_url = apps[0]
            owner_app, _, owner_url = apps[1]
            # pick a tile whose cache key the RING assigns to replica
            # B — deterministic (ring placement depends on the random
            # loopback ports, so probing a fixed few tiles can flake)
            quality = requester.pipeline.encode_signature()
            target = None
            for z in (0, 1):
                for c in (0, 1):
                    for x in (0, 64):
                        for y in (0, 64):
                            params = {
                                "imageId": "1", "z": str(z),
                                "c": str(c), "t": "0", "x": str(x),
                                "y": str(y), "w": "64", "h": "64",
                                "format": "png",
                            }
                            key = TileCtx.from_params(
                                params, None
                            ).cache_key(quality)
                            if requester.cache_plane.ring.owner(
                                key
                            ) == owner_url:
                                target = (
                                    f"/tile/1/{z}/{c}/0?x={x}&y={y}"
                                    "&w=64&h=64&format=png"
                                )
                                break
                        if target:
                            break
                    if target:
                        break
                if target:
                    break
            assert target, "ring assigned no probe key to replica B"
            async with aiohttp.ClientSession(
                cookies={"sessionid": "cookie-1"}
            ) as session:
                async with session.get(
                    requester_url + target
                ) as resp:
                    assert resp.status == 200
                    assert resp.headers.get("X-Cache") == "peer-hit"
            req_event = next(
                e for e in requester.recorder.events()
                if e["tags"].get("cache") == "peer-hit"
            )
            assert "peer" in req_event["stages_ms"]
            assert req_event["tags"]["peer_owner"] == owner_url
            tid = req_event["trace_id"]
            owner_events = owner_app.recorder.events(trace_id=tid)
            assert owner_events, (
                "owner kept no event for the forwarded trace"
            )
            assert owner_events[0]["peer_origin"] == requester_url
            assert owner_events[0]["parent_span_id"] == (
                req_event["span_id"]
            )
        finally:
            await cleanup()


# ---------------------------------------------------------------------------
# Zipkin reporter resilience (satellite) + dead-sink chaos lane
# ---------------------------------------------------------------------------


class TestZipkinReporter:
    def _span(self):
        tracer_span = TRACER.start_span  # noqa: F841 - doc anchor
        from omero_ms_pixel_buffer_tpu.obs.recorder import _RetroSpan

        return _RetroSpan(
            "a" * 32, "b" * 16, None, "t", time.time(), 0.01, {}
        )

    @pytest.mark.resilience
    def test_dead_sink_drops_and_breaks(self):
        from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD

        dead = f"http://127.0.0.1:{_free_port()}/api/v2/spans"
        reporter = ZipkinReporter(
            dead, "svc", flush_interval_s=0.01, post_timeout_s=0.2
        )
        try:
            before = reporter.dropped
            # enough batches to trip the consecutive-failure rule
            for _ in range(6):
                reporter._post([{"traceId": "x"}])
            assert reporter.dropped >= before + 6
            assert reporter._breaker.state == "open"
            assert "tracing:zipkin" in BOARD.snapshot()
            # with the breaker open, a batch drops WITHOUT a connect
            t0 = time.monotonic()
            reporter._post([{"traceId": "y"}])
            assert time.monotonic() - t0 < 0.05
        finally:
            reporter.close()
            # the breaker is process-wide (BOARD): heal it so later
            # reporter tests (test_zipkin) post instead of dropping
            reporter._breaker.reset()

    @pytest.mark.resilience
    def test_bounded_queue_counts_drops(self):
        dead = f"http://127.0.0.1:{_free_port()}/api/v2/spans"
        reporter = ZipkinReporter(
            dead, "svc", flush_interval_s=60.0, max_queue=4,
            post_timeout_s=0.2,
        )
        try:
            for _ in range(50):
                reporter.report(self._span())
            assert reporter.dropped >= 40
        finally:
            reporter.close()
            reporter._breaker.reset()

    @pytest.mark.resilience
    async def test_dead_zipkin_never_blocks_serving(self, tmp_path, loop):
        """Chaos lane: a dead Zipkin endpoint (tail reporter mode) —
        requests keep serving, fast, and the reporter just drops."""
        dead = f"http://127.0.0.1:{_free_port()}/api/v2/spans"
        app_obj = _make_app(
            tmp_path,
            extra={"http-tracing": {
                "enabled": False, "zipkin-url": dead,
            }},
        )
        assert TRACER.reporter is not None  # tail mode built it
        assert not TRACER.enabled
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        await client.start_server()
        try:
            t0 = time.monotonic()
            for _ in range(3):
                resp = await client.get(
                    "/tile/1/0/0/0?w=64&h=64&format=png", headers=AUTH
                )
                assert resp.status == 200
            assert time.monotonic() - t0 < 5.0
            assert app_obj.recorder.events()  # kept + ring intact
        finally:
            reporter = TRACER.reporter
            await client.close()
            configure_tracing(enabled=False, log_spans=False)
            if reporter is not None:
                reporter._breaker.reset()  # process-wide breaker

    def test_retro_spans_reach_reporter_only_when_tracing_off(self):
        class FakeReporter:
            def __init__(self):
                self.spans = []

            def report(self, span):
                self.spans.append(span)

        recorder = FlightRecorder(head_sample_rate=1.0)
        fake = FakeReporter()
        old_rep, old_en = TRACER.reporter, TRACER.enabled
        TRACER.reporter, TRACER.enabled = fake, False
        try:
            rec = recorder.start("/tile/1")
            with rec.stage("read"):
                pass
            recorder.complete(rec, 200)
            names = [s.name for s in fake.spans]
            assert "http:/tile/1" in names
            assert "stage:read" in names
            root = fake.spans[0]
            assert root.trace_id == rec.trace_id
            # live tracing on: the recorder must NOT double-report
            fake.spans.clear()
            TRACER.enabled = True
            rec2 = recorder.start("/tile/1")
            recorder.complete(rec2, 200)
            assert fake.spans == []
        finally:
            TRACER.reporter, TRACER.enabled = old_rep, old_en


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


class TestObsConfig:
    BASE = {"session-store": {"type": "memory"}}

    def test_defaults(self):
        config = Config.from_dict(self.BASE)
        assert config.obs.enabled is True
        assert config.obs.slow_threshold_ms == 300.0
        assert config.obs.head_sample_rate == 0.01
        assert config.obs.ring_size == 512

    def test_values_parse(self):
        config = Config.from_dict({
            **self.BASE,
            "obs": {
                "enabled": False, "slow-threshold-ms": 150,
                "head-sample-rate": 0.5, "ring-size": 32,
            },
        })
        assert config.obs.enabled is False
        assert config.obs.slow_threshold_ms == 150.0
        assert config.obs.head_sample_rate == 0.5
        assert config.obs.ring_size == 32

    def test_unknown_key_fails(self):
        with pytest.raises(ConfigError):
            Config.from_dict(
                {**self.BASE, "obs": {"slow-treshold-ms": 100}}
            )

    def test_bad_values_fail(self):
        for block in (
            {"head-sample-rate": 1.5},
            {"head-sample-rate": "lots"},
            {"ring-size": 0},
            {"slow-threshold-ms": -1},
        ):
            with pytest.raises(ConfigError):
                Config.from_dict({**self.BASE, "obs": block})
