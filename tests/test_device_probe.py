"""Device-probe failure policy: retries with doubling timeouts and
timestamped attempts, error results that expire (a healed tunnel
upgrades a running server), and non-blocking engine resolution (a
hanging probe must never stall a user request — VERDICT r3 items 2/3).
"""

import threading
import time

import numpy as np
import pytest

from omero_ms_pixel_buffer_tpu.runtime import device_probe


@pytest.fixture(autouse=True)
def fresh_probe():
    device_probe.reset()
    yield
    # unblock + drain any background probe before the next test
    inflight = device_probe._inflight
    if inflight is not None and inflight.is_alive():
        inflight.join(5)
    device_probe.reset()


class TestRetries:
    def test_doubling_timeouts_and_timestamped_attempts(self, monkeypatch):
        calls = []

        def fake_run_bounded(argv, timeout_s, env=None):
            calls.append(timeout_s)
            return {"error": f"timeout after {timeout_s:.0f}s"}

        monkeypatch.setattr(device_probe, "run_bounded", fake_run_bounded)
        monkeypatch.setattr(device_probe, "_fast_path_result", lambda: None)
        result = device_probe.probe(timeout_s=0.5, retries=3)
        assert calls == [0.5, 1.0, 2.0]
        assert "error" in result
        assert len(result["attempts"]) == 3
        for attempt in result["attempts"]:
            assert attempt["at"]  # timestamp proves the chip was tried
            assert "error" in attempt

    def test_stops_at_first_success(self, monkeypatch):
        seq = [
            {"error": "wedged"},
            {"backend": "tpu", "devices": ["d0"], "link_mbps": 42.0},
        ]
        monkeypatch.setattr(
            device_probe, "run_bounded",
            lambda argv, timeout_s, env=None: seq.pop(0),
        )
        monkeypatch.setattr(device_probe, "_fast_path_result", lambda: None)
        result = device_probe.probe(timeout_s=0.1, retries=3)
        assert result["backend"] == "tpu"
        assert len(result["attempts"]) == 2
        assert not seq  # both children consumed, no third


class TestErrorTtl:
    def test_error_expires_success_sticks(self, monkeypatch):
        monkeypatch.setenv("OMPB_DEVICE_PROBE_ERROR_TTL_S", "0.05")
        monkeypatch.setattr(device_probe, "_fast_path_result", lambda: None)
        seq = [{"error": "wedged"}]
        monkeypatch.setattr(
            device_probe, "run_bounded",
            lambda argv, timeout_s, env=None: (
                seq.pop(0) if seq
                else {"backend": "tpu", "devices": ["d0"],
                      "link_mbps": 42.0}
            ),
        )
        r1 = device_probe.probe(timeout_s=0.1, retries=1)
        assert "error" in r1
        # within the TTL the error is served from cache (no new child)
        assert device_probe.probe(timeout_s=0.1, retries=1) is r1
        time.sleep(0.06)
        r2 = device_probe.probe(timeout_s=0.1, retries=1)
        assert r2["backend"] == "tpu"
        # success caches for the process lifetime
        assert device_probe.probe(timeout_s=0.1, retries=1) is r2


class TestNonBlockingServing:
    def _hang(self, monkeypatch):
        release = threading.Event()

        def hanging_run_bounded(argv, timeout_s, env=None):
            release.wait(30)
            return {"error": "probe released by test"}

        monkeypatch.setattr(
            device_probe, "run_bounded", hanging_run_bounded
        )
        monkeypatch.setattr(device_probe, "_fast_path_result", lambda: None)
        return release

    def test_first_request_served_from_host_fast(
        self, monkeypatch, tmp_path
    ):
        from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
        from omero_ms_pixel_buffer_tpu.io.pixels_service import (
            ImageRegistry,
            PixelsService,
        )
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )
        from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

        release = self._hang(monkeypatch)
        try:
            img = np.arange(64 * 64, dtype=np.uint16).reshape(
                1, 1, 1, 64, 64
            )
            path = str(tmp_path / "img.ome.tiff")
            write_ome_tiff(path, img, tile_size=(32, 32))
            registry = ImageRegistry()
            registry.add(1, path)
            service = PixelsService(registry)
            try:
                pipe = TilePipeline(service, engine="auto")
                ctxs = [
                    TileCtx(image_id=1, z=0, c=0, t=0,
                            region=RegionDef(0, 0, 32, 32), format="png",
                            omero_session_key="k")
                ] * 2
                t0 = time.perf_counter()
                results = pipe.handle_batch(ctxs)
                elapsed = time.perf_counter() - t0
                assert all(r is not None for r in results)
                # the hung probe (30 s) must not be on the request path
                assert elapsed < 1.0, f"first batch took {elapsed:.1f}s"
                assert pipe._engine == "auto"  # not pinned while pending
            finally:
                service.close()
        finally:
            release.set()

    def test_app_startup_kicks_background_probe(self, monkeypatch):
        from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
        from omero_ms_pixel_buffer_tpu.utils.config import Config

        release = self._hang(monkeypatch)
        try:
            t0 = time.perf_counter()
            app = PixelBufferApp(
                Config.from_dict({"session-store": {"type": "memory"}})
            )
            assert time.perf_counter() - t0 < 5.0  # init never waits
            assert app.pipeline._engine == "auto"
            inflight = device_probe._inflight
            assert inflight is not None and inflight.is_alive()
        finally:
            release.set()

    def test_engine_upgrades_after_recovery(self, monkeypatch):
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        monkeypatch.setenv("OMPB_DEVICE_PROBE_ERROR_TTL_S", "0.05")
        monkeypatch.setenv("OMPB_DEVICE_PROBE_RETRIES", "1")
        monkeypatch.setenv("OMPB_DEVICE_PROBE_TIMEOUT_S", "0.1")
        monkeypatch.setenv("OMPB_DEVICE_MIN_MBPS", "1")
        monkeypatch.setattr(device_probe, "_fast_path_result", lambda: None)
        seq = [{"error": "wedged"}]
        monkeypatch.setattr(
            device_probe, "run_bounded",
            lambda argv, timeout_s, env=None: (
                seq.pop(0) if seq
                else {"backend": "tpu", "devices": ["d0"],
                      "link_mbps": 100.0}
            ),
        )
        pipe = TilePipeline(None, engine="auto")
        assert pipe.engine == "host"  # pending -> host, not pinned
        device_probe._inflight.join(5)
        assert pipe.engine == "host"  # error cached -> host, not pinned
        assert pipe._engine == "auto"
        time.sleep(0.06)  # error TTL expires -> next call re-probes
        pipe.engine
        device_probe._inflight.join(5)
        assert pipe.engine == "device"  # the healed chip is picked up
        assert pipe._engine == "device"  # and pinned
