"""Loop watchdog (utils/loop_watchdog.py) — the runtime twin of the
``loop-block`` static rule: a deliberately blocked loop must raise the
lag metric, count a blocked event, and surface on /healthz."""

import asyncio
import logging
import time

from omero_ms_pixel_buffer_tpu.utils.loop_watchdog import LoopWatchdog
from omero_ms_pixel_buffer_tpu.utils.metrics import REGISTRY


def test_blocked_loop_detected(caplog):
    async def scenario():
        wd = LoopWatchdog(interval_s=0.02, warn_after_s=0.1)
        wd.start()
        await asyncio.sleep(0.08)  # healthy beats first
        with caplog.at_level(
            logging.WARNING, "omero_ms_pixel_buffer_tpu.loop_watchdog"
        ):
            time.sleep(0.4)  # deliberately wedge the loop
            await asyncio.sleep(0.15)  # heartbeat observes + recovery
        snap = wd.snapshot()
        wd.stop()
        return snap

    snap = asyncio.run(scenario())
    # the 400 ms stall shows up as heartbeat lag...
    assert snap["max_lag_ms"] >= 200
    # ...and as an edge-triggered blocked event with a stack dump
    assert snap["blocked_events"] >= 1
    assert not snap["blocked"]  # recovered after the sleep
    blocked_logs = [
        r for r in caplog.records if "event loop blocked" in r.message
    ]
    assert blocked_logs
    # the dump names the offender: the time.sleep frame in this test
    assert "time.sleep(0.4)" in blocked_logs[0].getMessage()


def test_healthy_loop_stays_quiet():
    async def scenario():
        wd = LoopWatchdog(interval_s=0.02, warn_after_s=0.5)
        wd.start()
        await asyncio.sleep(0.2)
        snap = wd.snapshot()
        wd.stop()
        return snap

    snap = asyncio.run(scenario())
    assert snap["blocked_events"] == 0
    assert not snap["blocked"]


def test_stop_from_another_thread():
    """stop() may be called off the loop thread (management threads,
    signal handlers): the heartbeat cancel must hop through
    call_soon_threadsafe, not touch the Task directly."""
    import threading

    async def scenario():
        wd = LoopWatchdog(interval_s=0.02, warn_after_s=0.5)
        wd.start()
        await asyncio.sleep(0.05)
        t = threading.Thread(target=wd.stop)
        t.start()
        await asyncio.sleep(0.05)  # loop runs the threadsafe cancel
        t.join(timeout=2.0)
        assert not t.is_alive()
        assert wd._task is None and wd._thread is None

    asyncio.run(scenario())


def test_stop_is_idempotent_and_restartable():
    async def scenario():
        wd = LoopWatchdog(interval_s=0.02, warn_after_s=0.5)
        wd.start()
        wd.start()  # second arm is a no-op
        await asyncio.sleep(0.05)
        wd.stop()
        wd.stop()

    asyncio.run(scenario())


def test_metrics_exported():
    text = REGISTRY.exposition()
    assert "event_loop_lag_seconds" in text
    assert "event_loop_blocked_total" in text
    assert "event_loop_max_lag_seconds" in text


async def test_healthz_reports_loop_health(tmp_path, loop):
    """End-to-end: the app arms the watchdog on startup and /healthz
    carries its snapshot (watchdog tuned hot so the test is fast)."""
    from test_resilience import _make_app

    app_obj, client = await _make_app(
        tmp_path,
        resilience={"watchdog": {"interval-ms": 10, "warn-ms": 50}},
    )
    try:
        body = await (await client.get("/healthz")).json()
        assert body["loop"]["enabled"] is True
        assert body["loop"]["blocked_events"] == 0
        assert "max_lag_ms" in body["loop"]
    finally:
        await client.close()
    assert app_obj.watchdog is not None
    assert app_obj.watchdog._thread is None  # stopped on cleanup


async def test_watchdog_disabled_by_config(tmp_path, loop):
    from test_resilience import _make_app

    app_obj, client = await _make_app(
        tmp_path, resilience={"watchdog": {"enabled": False}}
    )
    try:
        body = await (await client.get("/healthz")).json()
        assert body["loop"] == {"enabled": False}
        assert app_obj.watchdog is None
    finally:
        await client.close()
